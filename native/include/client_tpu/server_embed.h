// In-process server embedding C API.
//
// The role the reference's java-api-bindings plays for tritonserver
// (reference: src/java-api-bindings/scripts/install_dependencies_and_build.sh
// — JavaCPP over the tritonserver C API): host the inference server INSIDE
// a C/C++/Java process. Here the engine is the Python ServerCore + JAX,
// reached by embedding CPython (libclient_tpu_embed.so links libpython and
// drives client_tpu.server.embed).
//
// Threading: every call is safe from any thread (the shim takes the GIL
// per call). Strings/buffers returned via ctpu_embed_* must be released
// with ctpu_embed_free().
//
// Request/response contract for infer: the KServe v2 two-part HTTP body
// (JSON header + concatenated binary tails). header_length < 0 means pure
// JSON. The same bytes every client library in this repo builds/parses.

#ifndef CLIENT_TPU_SERVER_EMBED_H_
#define CLIENT_TPU_SERVER_EMBED_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// Initialize the embedded interpreter (idempotent; called implicitly by
// ctpu_embed_server_create). repo_path may be NULL when client_tpu is
// importable from the environment. Returns 0 on success.
int ctpu_embed_init(const char* repo_path, char** error);

// Create a server; options_json e.g. {"models": ["simple"]} (empty = full
// default zoo). Returns a handle > 0, or 0 with *error set.
int64_t ctpu_embed_server_create(const char* options_json, char** error);

// One inference in the v2 two-part body format. On success fills
// *response/*response_len/*response_header_len (-1 = pure JSON) and
// returns 0. On failure returns nonzero and sets *error.
int ctpu_embed_infer(
    int64_t server, const char* model_name, const char* model_version,
    const uint8_t* body, size_t body_len, int64_t header_length,
    uint8_t** response, size_t* response_len, int64_t* response_header_len,
    char** error);

// Server (model_name = NULL/"") or model metadata as JSON.
int ctpu_embed_metadata(
    int64_t server, const char* model_name, char** json, char** error);

// Repository index / statistics as JSON.
int ctpu_embed_repository_index(int64_t server, char** json, char** error);
int ctpu_embed_statistics(
    int64_t server, const char* model_name, char** json, char** error);

// Model lifecycle (config_json may be NULL).
int ctpu_embed_load_model(
    int64_t server, const char* model_name, const char* config_json,
    char** error);
int ctpu_embed_unload_model(
    int64_t server, const char* model_name, char** error);

// Also expose the embedded core over HTTP; returns the bound port via
// *port (pass desired port or 0 for ephemeral).
int ctpu_embed_start_http(int64_t server, int* port, char** error);

// Destroy a server (stops any HTTP frontend it started).
int ctpu_embed_server_destroy(int64_t server, char** error);

// Release any buffer/string returned by this API.
void ctpu_embed_free(void* ptr);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // CLIENT_TPU_SERVER_EMBED_H_
