// Minimal HTTP/2 (RFC 7540) + HPACK (RFC 7541) client transport.
//
// Why this exists: the native GRPC client frames unary gRPC by hand and
// needs an HTTP/2 connection it can reuse. The image's libcurl (7.88 +
// nghttp2) wedges an h2c prior-knowledge connection after the first
// trailered response ("Error in the HTTP2 framing layer" on every
// subsequent request), and no grpc++/nghttp2 headers exist to link against.
// So the framework carries its own client-side h2: connection preface,
// SETTINGS/PING/WINDOW_UPDATE/GOAWAY handling, flow control both
// directions, and an HPACK codec (static + dynamic table, huffman decode)
// generated from the public RFC 7541 tables (hpack_tables.inc).
//
// Scope: cleartext h2c client (gRPC inside a trusted host/VPC, same as the
// reference's default insecure channel), one concurrent request per
// connection (callers pool connections for parallelism; streams multiplex
// fine at the protocol level but the blocking API keeps lifetimes simple).
// The send/recv halves of a stream are independent, which is what makes
// bi-di gRPC streaming (ModelStreamInfer) possible on top.
//
// Thread model: frames are written atomically under a send lock, stream
// state (windows, buffers) lives under a state lock, and at most one
// thread pumps the socket at a time (recv lock) — others wanting progress
// wait on a frame-arrival condition. This is exactly what a bi-di stream
// needs: one application thread in StreamSend, one reader thread in
// StreamRecv, neither corrupting the other's frames.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/tls.h"

namespace client_tpu {
namespace h2 {

using HeaderList = std::vector<std::pair<std::string, std::string>>;

// HPACK decoding context (connection-wide, ordered across HEADERS frames).
class HpackDecoder {
 public:
  HpackDecoder();
  Error Decode(const uint8_t* data, size_t size, HeaderList* out);
  void SetMaxTableSize(size_t size) { protocol_max_size_ = size; }

 private:
  Error DecodeInt(
      const uint8_t** p, const uint8_t* end, int prefix_bits, uint64_t* out);
  Error DecodeString(const uint8_t** p, const uint8_t* end, std::string* out);
  Error Lookup(uint64_t index, std::string* name, std::string* value);
  void Insert(const std::string& name, const std::string& value);
  void EvictTo(size_t target);

  std::vector<std::pair<std::string, std::string>> dynamic_;  // newest first
  size_t dynamic_size_ = 0;
  size_t max_size_ = 4096;
  size_t protocol_max_size_ = 4096;
};

class Connection {
 public:
  struct Response {
    int status = 0;
    std::map<std::string, std::string> headers;   // lowercased, incl trailers
    std::string body;
  };

  // Connects, sends the client preface, and performs the SETTINGS exchange.
  // `host_port` accepts "host:port" (cleartext h2c), or an "https://" url —
  // TLS via the system libssl runtime with ALPN "h2" (tls.h). Explicit
  // `tls` options force/configure TLS regardless of scheme.
  static Error Connect(
      std::unique_ptr<Connection>* conn, const std::string& host_port,
      int64_t timeout_ms = 10000, const tls::TlsOptions* tls_options = nullptr);
  ~Connection();

  // One blocking request/response exchange. `headers` are the non-pseudo
  // request headers; :method POST, :scheme http, :authority and :path are
  // synthesized. Returns transport errors; HTTP/gRPC-level status lives in
  // `out`. Not thread-safe — guard with a mutex or pool connections.
  Error Request(
      const std::string& path, const HeaderList& headers,
      const std::string& body, Response* out, int64_t timeout_ms = 0);

  // -- streaming primitives (bi-di gRPC) --------------------------------
  // Open a stream: send HEADERS (no END_STREAM). Returns the stream id.
  Error StreamOpen(
      const std::string& path, const HeaderList& headers, int32_t* stream_id);
  // Send one DATA chunk on the stream; end_stream closes the send half.
  Error StreamSend(
      int32_t stream_id, const void* data, size_t size, bool end_stream,
      int64_t timeout_ms = 0);
  // Receive events on the stream until one of: `min_bytes` of new body data
  // arrived, response headers/trailers completed, or stream closed.
  // Appends body bytes to `body`; headers/trailers merge into `headers`.
  // `closed` flips when the peer half-closed (END_STREAM).
  Error StreamRecv(
      int32_t stream_id, std::string* body,
      std::map<std::string, std::string>* headers, bool* closed,
      int64_t timeout_ms = 0);
  // Abort a stream (RST_STREAM CANCEL).
  Error StreamReset(int32_t stream_id);
  // Completion-queue primitive: pump until ANY listed stream is closed or
  // errored; *ready_id names it. Frames for non-listed streams are still
  // dispatched while pumping (this is what lets one thread reap a window
  // of concurrent in-flight RPCs — the multiplexed AsyncInfer model).
  Error StreamWaitAny(
      const std::vector<int32_t>& stream_ids, int32_t* ready_id,
      int64_t timeout_ms = 0);

  bool Alive() const { return alive_.load(); }
  // True once the peer sent GOAWAY: the socket may still be open (drain),
  // but new streams will be refused — callers must not reuse/pool this
  // connection (RFC 7540 §6.8: new work goes on a new connection).
  bool GoawayReceived() {
    std::lock_guard<std::mutex> lock(state_mutex_);
    return goaway_received_;
  }
  // Reusable = alive AND not draining.
  bool Reusable() { return Alive() && !GoawayReceived(); }
  const std::string& PeerDescription() const { return host_port_; }
  // Peer's advertised SETTINGS_MAX_CONCURRENT_STREAMS (RFC 7540 §6.5.2;
  // unset = unlimited). Multiplexing callers must not open more.
  int64_t PeerMaxConcurrentStreams() {
    std::lock_guard<std::mutex> lock(state_mutex_);
    return peer_max_concurrent_streams_;
  }

 private:
  explicit Connection(const std::string& host_port);

  Error SendAll(const void* data, size_t size, int64_t timeout_ms);
  // Reads + dispatches exactly one frame. Caller must hold recv_mutex_.
  Error RecvFrameLocked(int64_t timeout_ms);
  // Makes one unit of progress: pump a frame if this thread can take the
  // receiver role, else wait briefly for the active receiver's next frame.
  Error PumpOne(int64_t timeout_ms);
  Error SendFrame(
      uint8_t type, uint8_t flags, int32_t stream_id, const void* payload,
      size_t size, int64_t timeout_ms);
  Error Handshake(int64_t timeout_ms);
  Error PumpUntil(int32_t stream_id, int64_t timeout_ms);

  struct StreamState {
    std::string body;
    std::map<std::string, std::string> headers;
    bool headers_done = false;
    bool closed = false;          // peer sent END_STREAM / RST
    int64_t send_window = 65535;  // peer's flow-control budget for us
    Error error;                  // RST_STREAM arrival
  };

  // Raw-socket-contract IO (send(2)/recv(2) semantics on a non-blocking
  // fd), routed through the TLS session when one is active.
  ssize_t IoSend(const void* data, size_t size);
  ssize_t IoRecv(void* buf, size_t size);
  short IoPollEvents(short plain) const;

  std::string host_port_;
  int fd_ = -1;
  std::atomic<bool> alive_{false};
  std::unique_ptr<tls::TlsSession> tls_;

  std::mutex send_mutex_;   // whole-frame socket writes
  std::mutex state_mutex_;  // streams_, windows, next_stream_id_
  std::mutex recv_mutex_;   // at most one socket reader
  std::condition_variable frame_cv_;  // notified (state_mutex_) per frame

  int32_t next_stream_id_ = 1;
  std::string recv_buffer_;  // recv_mutex_ holder only
  HpackDecoder hpack_;       // recv_mutex_ holder only
  std::map<int32_t, StreamState> streams_;
  // peer settings (state_mutex_ past the handshake)
  int64_t peer_max_frame_size_ = 16384;
  int64_t peer_initial_window_ = 65535;
  int64_t peer_max_concurrent_streams_ = INT64_MAX;  // unset = unlimited
  int64_t conn_send_window_ = 65535;
  std::string goaway_debug_;
  bool goaway_received_ = false;  // state_mutex_; StreamOpen fails fast
};

}  // namespace h2
}  // namespace client_tpu
