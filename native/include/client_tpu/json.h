// Minimal JSON value/parser/writer for the KServe v2 protocol layer.
// Role of the reference's TritonJson glue (src/c++/library/json_utils.h),
// self-contained instead of depending on a vendored rapidjson.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace client_tpu {

class Json {
 public:
  enum class Type { kNull, kBool, kInt, kDouble, kString, kArray, kObject };

  Json() : type_(Type::kNull) {}
  explicit Json(bool b) : type_(Type::kBool), bool_(b) {}
  explicit Json(int64_t i) : type_(Type::kInt), int_(i) {}
  explicit Json(double d) : type_(Type::kDouble), double_(d) {}
  explicit Json(const std::string& s) : type_(Type::kString), string_(s) {}
  explicit Json(const char* s) : type_(Type::kString), string_(s) {}

  static Json Array() {
    Json j;
    j.type_ = Type::kArray;
    return j;
  }
  static Json Object() {
    Json j;
    j.type_ = Type::kObject;
    return j;
  }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::kNull; }
  bool is_object() const { return type_ == Type::kObject; }
  bool is_array() const { return type_ == Type::kArray; }

  bool AsBool() const { return type_ == Type::kBool ? bool_ : false; }
  int64_t AsInt() const {
    if (type_ == Type::kInt) return int_;
    if (type_ == Type::kDouble) return static_cast<int64_t>(double_);
    return 0;
  }
  double AsDouble() const {
    if (type_ == Type::kDouble) return double_;
    if (type_ == Type::kInt) return static_cast<double>(int_);
    return 0.0;
  }
  const std::string& AsString() const { return string_; }

  // object access
  bool Has(const std::string& key) const { return object_.count(key) > 0; }
  const Json& At(const std::string& key) const;  // null json if absent
  Json& Set(const std::string& key, Json value) {
    return object_[key] = std::move(value);
  }
  const std::map<std::string, Json>& items() const { return object_; }

  // array access
  size_t size() const { return array_.size(); }
  const Json& operator[](size_t i) const { return array_[i]; }
  void Append(Json value) { array_.push_back(std::move(value)); }

  std::string Dump() const;

  // Parses `text`; on success returns true and fills `out`.
  static bool Parse(const std::string& text, Json* out, std::string* error);

 private:
  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string string_;
  std::vector<Json> array_;
  std::map<std::string, Json> object_;
};

}  // namespace client_tpu
