// TLS socket layer for the native clients, over the SYSTEM libssl runtime.
//
// Why dlopen: this image ships /lib/x86_64-linux-gnu/libssl.so.3 (OpenSSL
// 3.0, the same library libcurl links) but NO OpenSSL headers, and the
// only headers around (a BoringSSL bundle) mismatch that runtime's ABI.
// So the handful of stable libssl entry points used here are declared by
// hand and resolved with dlopen/dlsym at first use — no build-time
// dependency, same runtime curl already proved works.
//
// Reference parity: HttpSslOptions (http_client.h:45-103) and grpc
// SslOptions (grpc_client.h:43-60) — CA bundle, client cert/key, peer and
// host verification. ALPN offers "h2" so the gRPC path negotiates HTTP/2.
#pragma once

#include <sys/types.h>

#include <memory>
#include <mutex>
#include <string>

#include "client_tpu/common.h"

namespace client_tpu {
namespace tls {

struct TlsOptions {
  bool use_tls = false;
  // Reference HttpSslOptions::verify_peer / verify_host.
  bool verify_peer = true;
  bool verify_host = true;
  // PEM CA bundle (HttpSslOptions::ca_info / grpc root_certificates);
  // empty = the system default verify paths.
  std::string ca_cert_file;
  // PEM client certificate chain + private key (mutual TLS).
  std::string client_cert_file;
  std::string client_key_file;
};

// One TLS client session over an already-connected non-blocking socket.
// Send/Recv follow the send(2)/recv(2) contract on a non-blocking fd:
// >0 bytes moved; 0 = orderly peer close (Recv); -1 with errno EAGAIN =
// retry after poll(fd, poll_events()).
class TlsSession {
 public:
  // Handshakes (blocking up to timeout_ms, polling the non-blocking fd).
  // `host` feeds SNI and hostname verification.
  static Error Create(
      std::unique_ptr<TlsSession>* out, int fd, const std::string& host,
      const TlsOptions& options, int64_t timeout_ms);
  ~TlsSession();

  ssize_t Send(const void* data, size_t size);
  ssize_t Recv(void* buf, size_t size);
  // Which poll event unblocks the last EAGAIN on each half (TLS
  // renegotiation can want POLLIN mid-write and vice versa). Tracked
  // separately per direction: a concurrent writer's WANT_WRITE must not
  // redirect a blocked reader to poll for POLLOUT.
  short SendPollEvents() const { return send_poll_events_; }
  short RecvPollEvents() const { return recv_poll_events_; }
  // Negotiated ALPN protocol ("h2", "http/1.1", or "" if none).
  const std::string& Alpn() const { return alpn_; }

 private:
  TlsSession() = default;
  void* ssl_ = nullptr;  // SSL*
  void* ctx_ = nullptr;  // SSL_CTX*
  // OpenSSL SSL objects are NOT safe for concurrent SSL_read/SSL_write
  // (shared rwstate + error state); the h2 layer has independent send and
  // recv locks, so this mutex serializes every libssl call on the session.
  std::mutex io_mutex_;
  short send_poll_events_ = 0x004 /*POLLOUT*/;
  short recv_poll_events_ = 0x001 /*POLLIN*/;
  std::string alpn_;
};

// True when the system libssl runtime loaded (TLS urls usable).
bool Available();

}  // namespace tls
}  // namespace client_tpu
