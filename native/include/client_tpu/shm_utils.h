// POSIX shared-memory helpers (role of the reference's shm_utils.h:
// CreateSharedMemoryRegion/Map/Close/Unlink/Unmap, shm_utils.cc:39-106).
#pragma once

#include <cstddef>
#include <string>

#include "client_tpu/common.h"

namespace client_tpu {

// shm_open(O_CREAT)+ftruncate; returns the fd in `shm_fd`.
Error CreateSharedMemoryRegion(
    const std::string& shm_key, size_t byte_size, int* shm_fd);
// Opens an existing region read/write.
Error OpenSharedMemoryRegion(const std::string& shm_key, int* shm_fd);
// mmap of [offset, offset+byte_size).
Error MapSharedMemory(
    int shm_fd, size_t offset, size_t byte_size, void** shm_addr);
Error CloseSharedMemory(int shm_fd);
Error UnlinkSharedMemoryRegion(const std::string& shm_key);
Error UnmapSharedMemory(void* shm_addr, size_t byte_size);

}  // namespace client_tpu
