// Minimal proto3 wire-format primitives for the GRPC client.
// The native twin of the Python schema codec (client_tpu/grpc/_wire.py):
// instead of generated stubs (the reference links protoc output,
// src/c++/library/grpc_client.cc), messages are hand-framed against the
// public KServe field numbers with a writer/reader pair. Wire rules:
// tag = (field_number << 3) | wire_type; wire types 0 varint, 1 fixed64,
// 2 length-delimited, 5 fixed32; proto3 scalars skip defaults; repeated
// numerics are packed on encode and accepted in both forms on decode.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace client_tpu {
namespace pb {

// ---------------------------------------------------------------------------
// writer
// ---------------------------------------------------------------------------

class Writer {
 public:
  explicit Writer(std::string* out) : out_(out) {}

  void Varint(uint64_t v) {
    while (v >= 0x80) {
      out_->push_back(static_cast<char>((v & 0x7F) | 0x80));
      v >>= 7;
    }
    out_->push_back(static_cast<char>(v));
  }
  void Tag(uint32_t field, uint32_t wire_type) {
    Varint((static_cast<uint64_t>(field) << 3) | wire_type);
  }

  // proto3 default-skipping scalar emitters
  void Uint64(uint32_t field, uint64_t v) {
    if (v == 0) return;
    Tag(field, 0);
    Varint(v);
  }
  void Int64(uint32_t field, int64_t v) {
    if (v == 0) return;
    Tag(field, 0);
    Varint(static_cast<uint64_t>(v));  // two's-complement 10-byte form
  }
  void Bool(uint32_t field, bool v) {
    if (!v) return;
    Tag(field, 0);
    Varint(1);
  }
  void String(uint32_t field, const std::string& v) {
    if (v.empty()) return;
    Tag(field, 2);
    Varint(v.size());
    out_->append(v);
  }
  void Bytes(uint32_t field, const void* data, size_t size) {
    Tag(field, 2);
    Varint(size);
    out_->append(static_cast<const char*>(data), size);
  }
  // length-delimited submessage from already-encoded payload
  void Submessage(uint32_t field, const std::string& payload) {
    Tag(field, 2);
    Varint(payload.size());
    out_->append(payload);
  }
  void PackedInt64(uint32_t field, const std::vector<int64_t>& vals) {
    if (vals.empty()) return;
    std::string inner;
    Writer w(&inner);
    for (int64_t v : vals) w.Varint(static_cast<uint64_t>(v));
    Submessage(field, inner);
  }

 private:
  std::string* out_;
};

// ---------------------------------------------------------------------------
// reader
// ---------------------------------------------------------------------------

// Cursor over a serialized message. Usage:
//   Reader r(data, size);
//   uint32_t field, wt;
//   while (r.Next(&field, &wt)) { switch (field) { ... default: r.Skip(wt); } }
// All getters validate bounds and flag ok()=false on truncation.
class Reader {
 public:
  Reader(const uint8_t* data, size_t size) : p_(data), end_(data + size) {}
  Reader(const char* data, size_t size)
      : Reader(reinterpret_cast<const uint8_t*>(data), size) {}

  bool ok() const { return ok_; }
  bool AtEnd() const { return p_ >= end_; }

  bool Next(uint32_t* field, uint32_t* wire_type) {
    if (!ok_ || AtEnd()) return false;
    uint64_t tag = Varint();
    if (!ok_) return false;
    *field = static_cast<uint32_t>(tag >> 3);
    *wire_type = static_cast<uint32_t>(tag & 0x7);
    return true;
  }

  uint64_t Varint() {
    uint64_t result = 0;
    int shift = 0;
    while (p_ < end_) {
      uint8_t b = *p_++;
      result |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return result;
      shift += 7;
      if (shift >= 70) break;
    }
    ok_ = false;
    return 0;
  }
  int64_t SignedVarint() { return static_cast<int64_t>(Varint()); }
  bool BoolVal() { return Varint() != 0; }

  // length-delimited payload; returns a view into the buffer (no copy)
  bool LengthDelimited(const uint8_t** data, size_t* size) {
    uint64_t len = Varint();
    // compare against remaining bytes — `p_ + len` can wrap for hostile
    // varint lengths and slip past the check
    if (!ok_ || len > static_cast<uint64_t>(end_ - p_)) {
      ok_ = false;
      return false;
    }
    *data = p_;
    *size = static_cast<size_t>(len);
    p_ += len;
    return true;
  }
  std::string StringVal() {
    const uint8_t* d;
    size_t n;
    if (!LengthDelimited(&d, &n)) return "";
    return std::string(reinterpret_cast<const char*>(d), n);
  }

  // packed-or-not repeated int64 (shape fields)
  void RepeatedInt64(uint32_t wire_type, std::vector<int64_t>* out) {
    if (wire_type == 2) {
      const uint8_t* d;
      size_t n;
      if (!LengthDelimited(&d, &n)) return;
      Reader inner(d, n);
      while (!inner.AtEnd() && inner.ok()) out->push_back(inner.SignedVarint());
      ok_ = ok_ && inner.ok();
    } else {
      out->push_back(SignedVarint());
    }
  }

  void Skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0:
        Varint();
        break;
      case 1:
        p_ += 8;
        break;
      case 2: {
        const uint8_t* d;
        size_t n;
        LengthDelimited(&d, &n);
        break;
      }
      case 5:
        p_ += 4;
        break;
      default:
        ok_ = false;
    }
    if (p_ > end_) ok_ = false;
  }

 private:
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

// ---------------------------------------------------------------------------
// gRPC message framing (5-byte prefix: compressed flag + u32 BE length)
// ---------------------------------------------------------------------------

inline void FrameMessage(
    const std::string& payload, std::string* out, bool compressed = false) {
  out->reserve(out->size() + 5 + payload.size());
  // gRPC message framing flag byte: 1 = payload is compressed with the
  // algorithm named by the grpc-encoding header
  out->push_back(compressed ? '\x01' : '\0');
  uint32_t n = static_cast<uint32_t>(payload.size());
  out->push_back(static_cast<char>((n >> 24) & 0xFF));
  out->push_back(static_cast<char>((n >> 16) & 0xFF));
  out->push_back(static_cast<char>((n >> 8) & 0xFF));
  out->push_back(static_cast<char>(n & 0xFF));
  out->append(payload);
}

// Parses one length-prefixed message from `data`; advances *pos. Returns
// false when fewer than 5 + len bytes remain.
inline bool UnframeMessage(
    const std::string& data, size_t* pos, const uint8_t** payload,
    size_t* payload_size, bool* compressed) {
  if (*pos + 5 > data.size()) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(data.data()) + *pos;
  *compressed = p[0] != 0;
  uint32_t n = (static_cast<uint32_t>(p[1]) << 24) |
               (static_cast<uint32_t>(p[2]) << 16) |
               (static_cast<uint32_t>(p[3]) << 8) | static_cast<uint32_t>(p[4]);
  if (*pos + 5 + n > data.size()) return false;
  *payload = p + 5;
  *payload_size = n;
  *pos += 5 + n;
  return true;
}

}  // namespace pb
}  // namespace client_tpu
