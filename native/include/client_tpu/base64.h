// base64 codec — the role of the reference's vendored libb64 (cencode.h):
// encoding raw shared-memory handles and model-file payloads for HTTP JSON.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace client_tpu {

std::string Base64Encode(const uint8_t* data, size_t size);
inline std::string Base64Encode(const std::string& s) {
  return Base64Encode(reinterpret_cast<const uint8_t*>(s.data()), s.size());
}
bool Base64Decode(const std::string& encoded, std::vector<uint8_t>* out);

}  // namespace client_tpu
