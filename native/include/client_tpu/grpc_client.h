// KServe v2 GRPC client over the framework's own HTTP/2 transport (h2.h).
// Role parity with the reference's src/c++/library/grpc_client.h:100
// (InferenceServerGrpcClient): sync Infer, callback AsyncInfer, InferMulti
// fan-out, bi-di streaming (StartStream/AsyncStreamInfer/StopStream), and
// the full admin/shm RPC surface. Design departure from the reference
// (grpc_client.cc:1094-1673, grpc++ stubs + completion queue): messages are
// proto3-framed by hand against the public KServe field numbers (pbwire.h,
// mirroring client_tpu/grpc/_messages.py) and carried as application/grpc
// over h2c — no grpc++, protoc, or libcurl dependency on this path.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/h2.h"
#include "client_tpu/json.h"

namespace client_tpu {

class InferenceServerGrpcClient {
 public:
  using OnComplete = std::function<void(InferResult*)>;
  using OnMultiComplete = std::function<void(std::vector<InferResult*>)>;
  // Stream callback: result may be null on stream error; error is
  // Error::Success() for normal responses (reference _InferStream semantics).
  using OnStreamResponse = std::function<void(InferResult*, const Error&)>;
  using Headers = std::map<std::string, std::string>;

  // `server_url`: "host:port" (cleartext h2c) or "https://host:port".
  // `ssl_options` configures TLS (CA bundle, client cert/key, verification)
  // and, with use_tls=true, forces TLS for scheme-less urls — the analog of
  // the reference grpc SslOptions (grpc_client.h:43-60).
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& server_url, bool verbose = false,
      const tls::TlsOptions& ssl_options = {});
  ~InferenceServerGrpcClient();

  Error IsServerLive(bool* live, const Headers& headers = {});
  Error IsServerReady(bool* ready, const Headers& headers = {});
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});

  Error ServerMetadata(Json* metadata, const Headers& headers = {});
  Error ModelMetadata(
      Json* metadata, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});
  Error ModelConfig(
      Json* config, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});
  Error ModelRepositoryIndex(Json* index, const Headers& headers = {});
  Error LoadModel(
      const std::string& model_name, const std::string& config = "",
      const Headers& headers = {});
  Error UnloadModel(const std::string& model_name, const Headers& headers = {});
  Error ModelInferenceStatistics(
      Json* stats, const std::string& model_name = "",
      const std::string& model_version = "", const Headers& headers = {});
  Error UpdateTraceSettings(
      Json* response, const std::string& model_name = "",
      const Json& settings = Json::Object(), const Headers& headers = {});
  Error GetTraceSettings(
      Json* settings, const std::string& model_name = "",
      const Headers& headers = {});
  Error UpdateLogSettings(
      Json* response, const Json& settings, const Headers& headers = {});
  Error GetLogSettings(Json* settings, const Headers& headers = {});

  Error SystemSharedMemoryStatus(
      Json* status, const std::string& name = "", const Headers& headers = {});
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0, const Headers& headers = {});
  Error UnregisterSystemSharedMemory(
      const std::string& name = "", const Headers& headers = {});
  Error TpuSharedMemoryStatus(
      Json* status, const std::string& name = "", const Headers& headers = {});
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int device_id, size_t byte_size, const Headers& headers = {});
  Error UnregisterTpuSharedMemory(
      const std::string& name = "", const Headers& headers = {});
  Error CudaSharedMemoryStatus(
      Json* status, const std::string& name = "", const Headers& headers = {});
  Error RegisterCudaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int device_id, size_t byte_size, const Headers& headers = {});
  Error UnregisterCudaSharedMemory(
      const std::string& name = "", const Headers& headers = {});

  // `compression_algorithm`: "gzip" | "deflate" | "" (= the client default
  // set via SetCompression). Reference parity: per-call
  // grpc_compression_algorithm (grpc_client.h Infer/AsyncInfer; Python
  // grpc/_client.py:1459-1565).
  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = {},
      const std::string& compression_algorithm = "");
  Error AsyncInfer(
      OnComplete callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = {},
      const std::string& compression_algorithm = "");
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs = {},
      const Headers& headers = {});
  Error AsyncInferMulti(
      OnMultiComplete callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs = {},
      const Headers& headers = {});

  // -- bi-di streaming (ModelStreamInfer) --------------------------------
  // Reference grpc_client.cc:1323-1416. `callback(result, error)` fires on
  // the reader thread per response. Pass "triton_grpc_error": "true" in
  // `headers` for true-status mode.
  Error StartStream(
      OnStreamResponse callback, const Headers& headers = {},
      uint64_t stream_timeout_us = 0);
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});
  Error StopStream();

  InferStat ClientInferStat();

  // Headers attached to every RPC (merged under per-call headers).
  void AddDefaultHeader(const std::string& key, const std::string& value) {
    std::lock_guard<std::mutex> lock(default_headers_mutex_);
    default_headers_[key] = value;
  }

  // In-flight window for AsyncInfer: how many RPCs the worker keeps open
  // concurrently on its multiplexed connection (completion-queue model).
  void SetAsyncConcurrency(size_t n) {
    std::lock_guard<std::mutex> lock(queue_mutex_);
    max_async_inflight_ = n == 0 ? 1 : n;
  }

  // Default message compression for infer RPCs and streams: "gzip",
  // "deflate", or "" (off). Per-call compression_algorithm overrides it.
  void SetCompression(const std::string& algorithm);
  std::string DefaultCompression();

 private:
  InferenceServerGrpcClient(
      const std::string& url, bool verbose, const tls::TlsOptions& ssl);

  // One unary RPC over a pooled connection.
  Error Call(
      const std::string& method, const std::string& request,
      std::string* response, const Headers& headers = {},
      uint64_t timeout_us = 0, const std::string& compression = "");
  std::unique_ptr<h2::Connection> AcquireConnection(Error* err);
  void ReleaseConnection(std::unique_ptr<h2::Connection> conn);

  struct AsyncRequest;
  void AsyncTransfer();
  void FinishAsync(AsyncRequest* request, InferResult* result);
  void FinishAsyncError(AsyncRequest* request, const Error& err);
  void StreamReader();

  std::string url_;
  bool verbose_;
  tls::TlsOptions ssl_options_;

  std::mutex pool_mutex_;
  std::vector<std::unique_ptr<h2::Connection>> idle_;

  std::thread worker_;
  std::mutex queue_mutex_;
  std::condition_variable queue_cv_;
  std::deque<AsyncRequest*> pending_;
  size_t max_async_inflight_ = 16;  // queue_mutex_
  std::atomic<bool> exiting_{false};

  // streaming state: dedicated connection + reader thread
  struct StreamCtx;
  std::mutex stream_mutex_;
  std::unique_ptr<StreamCtx> stream_;

  std::mutex stat_mutex_;
  InferStat infer_stat_;

  std::mutex default_headers_mutex_;
  Headers default_headers_;
  std::string default_compression_;  // default_headers_mutex_
  Headers MergedHeaders(const Headers& headers);
};

}  // namespace client_tpu
