// KServe v2 HTTP/REST client over libcurl.
// Role parity with the reference's src/c++/library/http_client.h:105 —
// sync Infer (curl easy), AsyncInfer (curl multi + worker thread), the full
// admin surface, two-part binary bodies with Inference-Header-Content-Length,
// and shared-memory registration including the tpusharedmemory family.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>

#include "client_tpu/common.h"
#include "client_tpu/json.h"

using CURL = void;
using CURLM = void;
struct curl_slist;

namespace client_tpu {

// TLS options for the libcurl transport — field-for-field the reference
// HttpSslOptions (/root/reference/src/c++/library/http_client.h:45-103),
// minus the CURLOPT_SSLVERSION pin (curl negotiates the best TLS version).
struct HttpSslOptions {
  bool verify_peer = true;   // CURLOPT_SSL_VERIFYPEER
  bool verify_host = true;   // CURLOPT_SSL_VERIFYHOST (2 when on)
  std::string ca_info;       // CURLOPT_CAINFO (PEM CA bundle path)
  std::string cert;          // CURLOPT_SSLCERT (client certificate path)
  std::string cert_type = "PEM";  // CURLOPT_SSLCERTTYPE: PEM | DER
  std::string key;           // CURLOPT_SSLKEY (client key path)
  std::string key_type = "PEM";   // CURLOPT_SSLKEYTYPE: PEM | DER
};

class InferenceServerHttpClient {
 public:
  using OnComplete = std::function<void(InferResult*)>;
  using OnMultiComplete = std::function<void(std::vector<InferResult*>)>;
  using Headers = std::map<std::string, std::string>;

  // `server_url` accepts "host:port" (http) or an explicit
  // "https://host:port"; `ssl_options` governs the TLS handshake for the
  // latter (applies to the sync easy handle and every async multi handle).
  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& server_url, bool verbose = false,
      const HttpSslOptions& ssl_options = {});
  ~InferenceServerHttpClient();

  Error IsServerLive(bool* live);
  Error IsServerReady(bool* ready);
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "");

  Error ServerMetadata(Json* metadata);
  Error ModelMetadata(
      Json* metadata, const std::string& model_name,
      const std::string& model_version = "");
  Error ModelConfig(
      Json* config, const std::string& model_name,
      const std::string& model_version = "");
  Error ModelRepositoryIndex(Json* index);
  Error LoadModel(
      const std::string& model_name, const std::string& config = "",
      const std::map<std::string, std::vector<char>>& files = {});
  Error UnloadModel(const std::string& model_name);
  Error ModelInferenceStatistics(
      Json* stats, const std::string& model_name = "",
      const std::string& model_version = "");
  Error UpdateTraceSettings(
      Json* response, const std::string& model_name = "",
      const Json& settings = Json::Object());
  Error GetTraceSettings(Json* settings, const std::string& model_name = "");
  Error UpdateLogSettings(Json* response, const Json& settings);
  Error GetLogSettings(Json* settings);

  Error SystemSharedMemoryStatus(Json* status, const std::string& name = "");
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0);
  Error UnregisterSystemSharedMemory(const std::string& name = "");
  Error TpuSharedMemoryStatus(Json* status, const std::string& name = "");
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle_b64,
      int device_id, size_t byte_size);
  Error UnregisterTpuSharedMemory(const std::string& name = "");
  Error CudaSharedMemoryStatus(Json* status, const std::string& name = "");
  Error RegisterCudaSharedMemory(
      const std::string& name, const std::string& raw_handle_b64,
      int device_id, size_t byte_size);
  Error UnregisterCudaSharedMemory(const std::string& name = "");

  // Offline marshaling (reference http_client.h:121-137): build/parse v2
  // infer payloads without a network round trip.
  static Error GenerateRequestBody(
      std::string* request_body, size_t* header_length,
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});
  static Error ParseResponseBody(
      InferResult** result, std::string&& response_body, size_t header_length);

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});
  Error AsyncInfer(
      OnComplete callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});

  // Batch variants with option/output broadcasting (reference
  // cc_client_test.cc:300-1200): a single options/outputs entry applies to
  // every request; otherwise sizes must match the request count.
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs = {});
  Error AsyncInferMulti(
      OnMultiComplete callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs = {});

  InferStat ClientInferStat();

  // Headers sent with every request (auth tokens etc. — the role of the
  // reference's per-call Headers param / the Python plugin hook).
  void AddDefaultHeader(const std::string& key, const std::string& value) {
    std::lock_guard<std::mutex> lock(headers_mutex_);
    default_headers_[key] = value;
  }


 private:
  InferenceServerHttpClient(
      const std::string& url, bool verbose, const HttpSslOptions& ssl);
  void ApplySslOptions(CURL* easy);

  Error Perform(
      const std::string& path, const std::string* body, long* http_code,
      std::string* response);
  Error Get(const std::string& path, long* http_code, std::string* response);
  Error Post(
      const std::string& path, const std::string& body, long* http_code,
      std::string* response);
  Error GetJson(const std::string& path, Json* out);
  Error PostJson(const std::string& path, const std::string& body, Json* out);
  Error ShmStatus(const std::string& family, const std::string& name, Json* out);
  Error ShmRegisterHandle(
      const std::string& family, const std::string& name,
      const std::string& raw_handle_b64, int device_id, size_t byte_size);
  Error ShmUnregister(const std::string& family, const std::string& name);

  struct AsyncRequest;
  void AsyncTransfer();

  std::string url_;
  HttpSslOptions ssl_options_;
  bool verbose_;
  CURL* easy_ = nullptr;  // shared handle for sync calls
  std::mutex easy_mutex_;

  CURLM* multi_ = nullptr;
  std::thread worker_;
  std::mutex multi_mutex_;
  std::condition_variable multi_cv_;
  std::deque<AsyncRequest*> pending_;
  std::atomic<bool> exiting_{false};

  std::mutex stat_mutex_;
  InferStat infer_stat_;

  std::mutex headers_mutex_;
  Headers default_headers_;
  struct curl_slist* DefaultHeaderList(struct curl_slist* list);
};

}  // namespace client_tpu
