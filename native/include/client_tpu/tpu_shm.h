// TPU shared-memory shim: the native side of utils.tpu_shared_memory.
// Role of the reference's ipc.h/cuda path (cudaMalloc+cudaIpcGetMemHandle):
// a region is a POSIX host window whose serialized handle is a base64 JSON
// descriptor interoperable with the Python module (same "tpu_shared_memory"
// kind, shm_key, byte_size, device_id fields), so a C++ producer can feed a
// Python consumer and vice versa. Device binding happens at the XLA layer
// in-process; cross-process transport is the host window.
#pragma once

#include <cstdint>
#include <string>

#include "client_tpu/common.h"

namespace client_tpu {

class TpuShmRegion {
 public:
  // Allocates a fresh region (shm key auto-generated when empty).
  static Error Create(
      TpuShmRegion** region, const std::string& name, size_t byte_size,
      int device_id = 0, const std::string& shm_key = "");
  // Attaches from a serialized raw handle (base64 JSON descriptor).
  static Error Attach(TpuShmRegion** region, const std::string& raw_handle);

  ~TpuShmRegion();

  const std::string& Name() const { return name_; }
  const std::string& ShmKey() const { return shm_key_; }
  size_t ByteSize() const { return byte_size_; }
  int DeviceId() const { return device_id_; }
  uint8_t* Data() const { return static_cast<uint8_t*>(addr_); }

  // Serialized descriptor for register_tpu_shared_memory.
  std::string RawHandle() const;

  Error Write(const void* src, size_t byte_size, size_t offset = 0);
  Error Read(void* dst, size_t byte_size, size_t offset = 0) const;

 private:
  TpuShmRegion() = default;

  std::string name_;
  std::string shm_key_;
  size_t byte_size_ = 0;
  int device_id_ = 0;
  bool owned_ = false;  // created (unlink on destroy) vs attached
  int fd_ = -1;
  void* addr_ = nullptr;
};

}  // namespace client_tpu
