// Shared value types for the native client library.
// Role parity with the reference's src/c++/library/common.h: Error (:61-83),
// InferOptions (:164-231), InferInput (:237-394), InferRequestedOutput
// (:400-482), InferResult (:488-563), RequestTimers (:568-648),
// InferStat (:93-114) — re-designed around the v2 protocol rather than
// translated.
#pragma once

#include <chrono>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace client_tpu {

class Error {
 public:
  Error() = default;
  explicit Error(const std::string& msg) : ok_(false), msg_(msg) {}
  static Error Success() { return Error(); }

  bool IsOk() const { return ok_; }
  const std::string& Message() const { return msg_; }
  explicit operator bool() const { return !ok_; }  // true when error

 private:
  bool ok_ = true;
  std::string msg_;
};

struct InferOptions {
  explicit InferOptions(const std::string& model_name_in)
      : model_name(model_name_in) {}

  std::string model_name;
  std::string model_version;
  std::string request_id;
  uint64_t sequence_id = 0;
  std::string sequence_id_str;  // string-form correlation id
  bool sequence_start = false;
  bool sequence_end = false;
  uint64_t priority = 0;
  uint64_t server_timeout_us = 0;
  uint64_t client_timeout_us = 0;
  bool enable_empty_final_response = false;
  std::map<std::string, std::string> request_parameters;
};

// An input tensor: metadata plus either scatter-gather host buffers or a
// shared-memory placement.
class InferInput {
 public:
  static Error Create(
      InferInput** result, const std::string& name,
      const std::vector<int64_t>& shape, const std::string& datatype);

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  const std::vector<int64_t>& Shape() const { return shape_; }
  Error SetShape(const std::vector<int64_t>& shape) {
    shape_ = shape;
    return Error::Success();
  }

  // Appends a raw chunk (no copy; caller keeps it alive until the request
  // completes). Multiple appends form a scatter-gather list.
  Error AppendRaw(const uint8_t* input, size_t input_byte_size);
  Error AppendRaw(const std::vector<uint8_t>& input) {
    return AppendRaw(input.data(), input.size());
  }
  // Appends BYTES elements from strings (serialized with 4B LE prefixes).
  Error AppendFromString(const std::vector<std::string>& input);

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  Error Reset();

  // encoder-facing
  bool InSharedMemory() const { return !shm_region_.empty(); }
  const std::string& SharedMemoryRegion() const { return shm_region_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }
  uint64_t ByteSize() const { return total_byte_size_; }
  const std::vector<std::pair<const uint8_t*, size_t>>& Buffers() const {
    return buffers_;
  }

 private:
  InferInput(
      const std::string& name, const std::vector<int64_t>& shape,
      const std::string& datatype)
      : name_(name), shape_(shape), datatype_(datatype) {}

  std::string name_;
  std::vector<int64_t> shape_;
  std::string datatype_;
  std::vector<std::pair<const uint8_t*, size_t>> buffers_;
  // deque: buffers_ records pointers into these strings, so elements must
  // never move on growth (a vector would dangle them on reallocation)
  std::deque<std::string> owned_;
  uint64_t total_byte_size_ = 0;
  std::string shm_region_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

class InferRequestedOutput {
 public:
  static Error Create(
      InferRequestedOutput** result, const std::string& name,
      size_t class_count = 0);

  const std::string& Name() const { return name_; }
  size_t ClassCount() const { return class_count_; }
  bool BinaryData() const { return binary_data_; }
  void SetBinaryData(bool b) { binary_data_ = b; }

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  Error UnsetSharedMemory();

  bool InSharedMemory() const { return !shm_region_.empty(); }
  const std::string& SharedMemoryRegion() const { return shm_region_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

 private:
  InferRequestedOutput(const std::string& name, size_t class_count)
      : name_(name), class_count_(class_count) {}

  std::string name_;
  size_t class_count_;
  bool binary_data_ = true;
  std::string shm_region_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

// The result of an inference: decoded response metadata + zero-copy views
// into the response body for binary outputs.
class InferResult {
 public:
  virtual ~InferResult() = default;

  virtual Error ModelName(std::string* name) const = 0;
  virtual Error ModelVersion(std::string* version) const = 0;
  virtual Error Id(std::string* id) const = 0;
  virtual Error OutputNames(std::vector<std::string>* names) const = 0;
  virtual Error Shape(
      const std::string& output_name, std::vector<int64_t>* shape) const = 0;
  virtual Error Datatype(
      const std::string& output_name, std::string* datatype) const = 0;
  // Zero-copy view into the response buffer; valid while the result lives.
  virtual Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const = 0;
  virtual Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const = 0;
  virtual Error IsFinalResponse(bool* is_final) const = 0;
  virtual Error IsNullResponse(bool* is_null) const = 0;
  virtual std::string DebugString() const = 0;
  virtual Error RequestStatus() const = 0;
};

// Monotonic nanosecond capture points per request; kinds extend the
// reference's six with TPU device-transfer points.
class RequestTimers {
 public:
  enum class Kind {
    REQUEST_START,
    REQUEST_END,
    SEND_START,
    SEND_END,
    RECV_START,
    RECV_END,
    H2D_START,
    H2D_END,
    D2H_START,
    D2H_END,
    COUNT_,
  };

  void Capture(Kind kind) {
    ts_[static_cast<size_t>(kind)] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count();
  }
  uint64_t DurationNs(Kind start, Kind end) const {
    uint64_t s = ts_[static_cast<size_t>(start)];
    uint64_t e = ts_[static_cast<size_t>(end)];
    return (s == 0 || e < s) ? 0 : e - s;
  }

 private:
  uint64_t ts_[static_cast<size_t>(Kind::COUNT_)] = {};
};

struct InferStat {
  uint64_t completed_request_count = 0;
  uint64_t cumulative_total_request_time_ns = 0;
  uint64_t cumulative_send_time_ns = 0;
  uint64_t cumulative_receive_time_ns = 0;

  void Update(const RequestTimers& timers) {
    completed_request_count++;
    cumulative_total_request_time_ns += timers.DurationNs(
        RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END);
    cumulative_send_time_ns += timers.DurationNs(
        RequestTimers::Kind::SEND_START, RequestTimers::Kind::SEND_END);
    cumulative_receive_time_ns += timers.DurationNs(
        RequestTimers::Kind::RECV_START, RequestTimers::Kind::RECV_END);
  }
};

using OnCompleteFn = void (*)(InferResult* result, void* userp);

// BYTES wire helpers (4-byte LE length prefix per element).
void SerializeStrings(
    const std::vector<std::string>& input, std::string* output);
Error DeserializeStrings(
    const uint8_t* buf, size_t byte_size, std::vector<std::string>* output);

}  // namespace client_tpu
