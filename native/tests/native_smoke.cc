// Offline + online smoke tests for the native library.
// Offline: json/base64/BYTES-serialization/shm/tpu-shm round trips.
// Online (CLIENT_TPU_TEST_URL set, e.g. 127.0.0.1:8000): full client flow
// against a live v2 server — health, metadata, sync Infer, AsyncInfer,
// system + tpu shared-memory inference.
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "client_tpu/base64.h"
#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"
#include "client_tpu/http_client.h"
#include "client_tpu/pbwire.h"
#include "client_tpu/json.h"
#include "client_tpu/shm_utils.h"
#include "client_tpu/tpu_shm.h"

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAILED at %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

#define CHECK_OK(expr)                                                  \
  do {                                                                  \
    client_tpu::Error err_ = (expr);                                    \
    if (err_) {                                                         \
      fprintf(stderr, "FAILED at %s:%d: %s\n", __FILE__, __LINE__,      \
              err_.Message().c_str());                                  \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

using namespace client_tpu;

void TestJson() {
  Json parsed;
  std::string error;
  CHECK(Json::Parse(
      R"({"a": 1, "b": [1.5, "x\n", true, null], "c": {"d": -3}})", &parsed,
      &error));
  CHECK(parsed.At("a").AsInt() == 1);
  CHECK(parsed.At("b").size() == 4);
  CHECK(parsed.At("b")[0].AsDouble() == 1.5);
  CHECK(parsed.At("b")[1].AsString() == "x\n");
  CHECK(parsed.At("b")[2].AsBool());
  CHECK(parsed.At("b")[3].is_null());
  CHECK(parsed.At("c").At("d").AsInt() == -3);
  // round trip
  Json again;
  CHECK(Json::Parse(parsed.Dump(), &again, &error));
  CHECK(again.At("c").At("d").AsInt() == -3);
  CHECK(!Json::Parse("{bad", &again, &error));
  printf("ok json\n");
}

void TestBase64() {
  const uint8_t data[] = {0x00, 0x01, 0xFE, 0xFF, 0x7F, 0x80, 0x41};
  std::string encoded = Base64Encode(data, sizeof(data));
  std::vector<uint8_t> decoded;
  CHECK(Base64Decode(encoded, &decoded));
  CHECK(decoded.size() == sizeof(data));
  CHECK(memcmp(decoded.data(), data, sizeof(data)) == 0);
  CHECK(Base64Encode(reinterpret_cast<const uint8_t*>("ab"), 2) == "YWI=");
  printf("ok base64\n");
}

void TestStringsSerialization() {
  std::vector<std::string> input = {"hello", "", std::string("\x00\x01", 2)};
  std::string serialized;
  SerializeStrings(input, &serialized);
  CHECK(serialized.size() == 4 * 3 + 5 + 0 + 2);
  std::vector<std::string> output;
  CHECK_OK(DeserializeStrings(
      reinterpret_cast<const uint8_t*>(serialized.data()), serialized.size(),
      &output));
  CHECK(output == input);
  std::vector<std::string> bad;
  CHECK(DeserializeStrings(
      reinterpret_cast<const uint8_t*>("\x05\x00\x00\x00"), 4, &bad));
  printf("ok strings\n");
}

void TestShm() {
  const char* key = "/ctpu_native_smoke";
  int fd = -1;
  CHECK_OK(CreateSharedMemoryRegion(key, 256, &fd));
  void* addr = nullptr;
  CHECK_OK(MapSharedMemory(fd, 0, 256, &addr));
  memcpy(addr, "native", 6);

  int fd2 = -1;
  CHECK_OK(OpenSharedMemoryRegion(key, &fd2));
  void* addr2 = nullptr;
  CHECK_OK(MapSharedMemory(fd2, 0, 256, &addr2));
  CHECK(memcmp(addr2, "native", 6) == 0);

  CHECK_OK(UnmapSharedMemory(addr, 256));
  CHECK_OK(UnmapSharedMemory(addr2, 256));
  CHECK_OK(CloseSharedMemory(fd));
  CHECK_OK(CloseSharedMemory(fd2));
  CHECK_OK(UnlinkSharedMemoryRegion(key));
  printf("ok shm\n");
}

void TestTpuShm() {
  TpuShmRegion* region = nullptr;
  CHECK_OK(TpuShmRegion::Create(&region, "native_region", 128));
  int32_t values[4] = {1, 2, 3, 4};
  CHECK_OK(region->Write(values, sizeof(values)));
  // attach through the serialized handle (the cross-process path)
  std::string handle = region->RawHandle();
  TpuShmRegion* attached = nullptr;
  CHECK_OK(TpuShmRegion::Attach(&attached, handle));
  int32_t readback[4] = {};
  CHECK_OK(attached->Read(readback, sizeof(readback)));
  CHECK(memcmp(values, readback, sizeof(values)) == 0);
  CHECK(attached->ByteSize() == 128);
  // bounds
  CHECK(region->Write(values, sizeof(values), 126));
  delete attached;
  delete region;
  printf("ok tpu_shm\n");
}

void TestOnline(const std::string& url) {
  std::unique_ptr<InferenceServerHttpClient> client;
  CHECK_OK(InferenceServerHttpClient::Create(&client, url));

  bool live = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK(live);
  bool ready = false;
  CHECK_OK(client->IsModelReady(&ready, "simple"));
  CHECK(ready);

  Json metadata;
  CHECK_OK(client->ServerMetadata(&metadata));
  CHECK(!metadata.At("name").AsString().empty());
  Json model_md;
  CHECK_OK(client->ModelMetadata(&model_md, "simple"));
  CHECK(model_md.At("inputs").size() == 2);

  // sync infer: INT32 sum/diff
  int32_t input0[16], input1[16];
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 1;
  }
  InferInput* in0;
  InferInput* in1;
  InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  CHECK_OK(in0->AppendRaw(reinterpret_cast<uint8_t*>(input0), sizeof(input0)));
  CHECK_OK(in1->AppendRaw(reinterpret_cast<uint8_t*>(input1), sizeof(input1)));

  InferOptions options("simple");
  options.request_id = "native-1";
  InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, {in0, in1}));
  const uint8_t* buf;
  size_t byte_size;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  CHECK(byte_size == sizeof(input0));
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK(sums[i] == input0[i] + input1[i]);
  std::string id;
  CHECK_OK(result->Id(&id));
  CHECK(id == "native-1");
  delete result;
  printf("ok online sync infer\n");

  // async infer
  std::mutex mu;
  std::condition_variable cv;
  int remaining = 4;
  bool all_ok = true;
  for (int r = 0; r < 4; ++r) {
    CHECK_OK(client->AsyncInfer(
        [&](InferResult* async_result) {
          const uint8_t* abuf;
          size_t asize;
          bool ok = async_result->RequestStatus().IsOk() &&
                    async_result->RawData("OUTPUT1", &abuf, &asize).IsOk();
          if (ok) {
            const int32_t* diffs = reinterpret_cast<const int32_t*>(abuf);
            for (int i = 0; i < 16; ++i) {
              ok = ok && diffs[i] == input0[i] - input1[i];
            }
          }
          delete async_result;
          std::lock_guard<std::mutex> lock(mu);
          all_ok = all_ok && ok;
          if (--remaining == 0) cv.notify_one();
        },
        options, {in0, in1}));
  }
  {
    std::unique_lock<std::mutex> lock(mu);
    CHECK(cv.wait_for(lock, std::chrono::seconds(30), [&] {
      return remaining == 0;
    }));
  }
  CHECK(all_ok);
  printf("ok online async infer\n");

  // JSON-mode output (binary_data=false): readable through the same accessor
  InferRequestedOutput* json_out;
  InferRequestedOutput::Create(&json_out, "OUTPUT0");
  json_out->SetBinaryData(false);
  InferResult* json_result = nullptr;
  CHECK_OK(client->Infer(&json_result, options, {in0, in1}, {json_out}));
  const uint8_t* jbuf;
  size_t jsize;
  CHECK_OK(json_result->RawData("OUTPUT0", &jbuf, &jsize));
  CHECK(jsize == sizeof(input0));
  const int32_t* jsums = reinterpret_cast<const int32_t*>(jbuf);
  for (int i = 0; i < 16; ++i) CHECK(jsums[i] == input0[i] + input1[i]);
  delete json_result;
  delete json_out;
  printf("ok online json-mode output\n");

  // tpu shared-memory inference: inputs and outputs via regions
  TpuShmRegion* rin = nullptr;
  TpuShmRegion* rout = nullptr;
  CHECK_OK(TpuShmRegion::Create(&rin, "native_in", 128));
  CHECK_OK(TpuShmRegion::Create(&rout, "native_out", 128));
  CHECK_OK(rin->Write(input0, 64, 0));
  CHECK_OK(rin->Write(input1, 64, 64));
  CHECK_OK(client->RegisterTpuSharedMemory("native_in", rin->RawHandle(), 0, 128));
  CHECK_OK(
      client->RegisterTpuSharedMemory("native_out", rout->RawHandle(), 0, 128));

  in0->SetSharedMemory("native_in", 64, 0);
  in1->SetSharedMemory("native_in", 64, 64);
  InferRequestedOutput* out0;
  InferRequestedOutput* out1;
  InferRequestedOutput::Create(&out0, "OUTPUT0");
  InferRequestedOutput::Create(&out1, "OUTPUT1");
  out0->SetSharedMemory("native_out", 64, 0);
  out1->SetSharedMemory("native_out", 64, 64);

  InferResult* shm_result = nullptr;
  CHECK_OK(client->Infer(&shm_result, options, {in0, in1}, {out0, out1}));
  delete shm_result;
  int32_t shm_sums[16], shm_diffs[16];
  CHECK_OK(rout->Read(shm_sums, 64, 0));
  CHECK_OK(rout->Read(shm_diffs, 64, 64));
  for (int i = 0; i < 16; ++i) {
    CHECK(shm_sums[i] == input0[i] + input1[i]);
    CHECK(shm_diffs[i] == input0[i] - input1[i]);
  }
  Json status;
  CHECK_OK(client->TpuSharedMemoryStatus(&status));
  CHECK(status.size() == 2);
  CHECK_OK(client->UnregisterTpuSharedMemory(""));
  delete rin;
  delete rout;
  printf("ok online tpu shm infer\n");

  // InferMulti / AsyncInferMulti with option broadcasting
  in0->Reset();
  in1->Reset();
  in0->AppendRaw(reinterpret_cast<uint8_t*>(input0), sizeof(input0));
  in1->AppendRaw(reinterpret_cast<uint8_t*>(input1), sizeof(input1));
  std::vector<InferResult*> multi_results;
  CHECK_OK(client->InferMulti(
      &multi_results, {options}, {{in0, in1}, {in0, in1}, {in0, in1}}));
  CHECK(multi_results.size() == 3);
  for (auto* r : multi_results) {
    const uint8_t* mbuf;
    size_t msize;
    CHECK_OK(r->RawData("OUTPUT0", &mbuf, &msize));
    CHECK(reinterpret_cast<const int32_t*>(mbuf)[3] ==
          input0[3] + input1[3]);
    delete r;
  }
  {
    std::mutex mmu;
    std::condition_variable mcv;
    bool multi_done = false;
    CHECK_OK(client->AsyncInferMulti(
        [&](std::vector<InferResult*> async_results) {
          bool ok = async_results.size() == 2;
          for (auto* r : async_results) {
            ok = ok && r->RequestStatus().IsOk();
            delete r;
          }
          std::lock_guard<std::mutex> lock(mmu);
          multi_done = ok;
          mcv.notify_one();
        },
        {options}, {{in0, in1}, {in0, in1}}));
    std::unique_lock<std::mutex> lock(mmu);
    CHECK(mcv.wait_for(lock, std::chrono::seconds(30), [&] {
      return multi_done;
    }));
  }
  printf("ok online infer multi\n");

  // stats reflect the traffic
  InferStat stat = client->ClientInferStat();
  CHECK(stat.completed_request_count >= 6);
  Json server_stats;
  CHECK_OK(client->ModelInferenceStatistics(&server_stats, "simple"));
  CHECK(server_stats.At("model_stats").size() == 1);

  delete in0;
  delete in1;
  delete out0;
  delete out1;
  printf("ok online stats\n");
}

void TestOfflineMarshaling() {
  // GenerateRequestBody/ParseResponseBody round trip with no server
  int32_t values[4] = {5, 6, 7, 8};
  InferInput* input = nullptr;
  InferInput::Create(&input, "IN", {4}, "INT32");
  input->AppendRaw(reinterpret_cast<uint8_t*>(values), sizeof(values));
  InferOptions options("m");
  std::string body;
  size_t header_length = 0;
  CHECK_OK(InferenceServerHttpClient::GenerateRequestBody(
      &body, &header_length, options, {input}));
  CHECK(header_length > 0 && body.size() == header_length + sizeof(values));
  Json header;
  std::string perr;
  CHECK(Json::Parse(body.substr(0, header_length), &header, &perr));
  CHECK(header.At("inputs")[0].At("name").AsString() == "IN");
  delete input;

  // a response body built by hand parses back through the public API
  Json resp = Json::Object();
  Json out = Json::Object();
  out.Set("name", Json("OUT"));
  out.Set("datatype", Json("INT32"));
  Json shape = Json::Array();
  shape.Append(Json(static_cast<int64_t>(4)));
  out.Set("shape", std::move(shape));
  Json params = Json::Object();
  params.Set("binary_data_size", Json(static_cast<int64_t>(16)));
  out.Set("parameters", std::move(params));
  Json outs = Json::Array();
  outs.Append(std::move(out));
  resp.Set("outputs", std::move(outs));
  std::string resp_header = resp.Dump();
  std::string resp_body = resp_header;
  resp_body.append(reinterpret_cast<char*>(values), sizeof(values));
  InferResult* result = nullptr;
  CHECK_OK(InferenceServerHttpClient::ParseResponseBody(
      &result, std::move(resp_body), resp_header.size()));
  const uint8_t* buf;
  size_t size;
  CHECK_OK(result->RawData("OUT", &buf, &size));
  CHECK(size == 16 && memcmp(buf, values, 16) == 0);
  delete result;
  printf("ok offline marshaling\n");
}


// pbwire codec round trips (offline): writer output parses back through the
// reader, matching the Python _wire.py semantics for the same field layouts.
void TestPbWire() {
  std::string msg;
  pb::Writer w(&msg);
  w.String(1, "abc");
  w.Int64(2, -5);
  w.Uint64(3, 1ull << 40);
  w.Bool(4, true);
  w.PackedInt64(5, {1, 16, -2});
  w.Bytes(6, "\x00\x01", 2);
  pb::Reader r(msg.data(), msg.size());
  uint32_t field, wt;
  std::string s;
  int64_t i2 = 0;
  uint64_t u3 = 0;
  bool b4 = false;
  std::vector<int64_t> packed;
  std::string bytes;
  while (r.Next(&field, &wt)) {
    switch (field) {
      case 1: s = r.StringVal(); break;
      case 2: i2 = r.SignedVarint(); break;
      case 3: u3 = r.Varint(); break;
      case 4: b4 = r.BoolVal(); break;
      case 5: r.RepeatedInt64(wt, &packed); break;
      case 6: bytes = r.StringVal(); break;
      default: r.Skip(wt);
    }
  }
  CHECK(r.ok());
  CHECK(s == "abc");
  CHECK(i2 == -5);
  CHECK(u3 == (1ull << 40));
  CHECK(b4);
  CHECK(packed.size() == 3 && packed[0] == 1 && packed[1] == 16 && packed[2] == -2);
  CHECK(bytes.size() == 2 && bytes[0] == 0 && bytes[1] == 1);
  // gRPC message framing
  std::string framed;
  pb::FrameMessage(msg, &framed);
  CHECK(framed.size() == msg.size() + 5);
  size_t pos = 0;
  const uint8_t* payload;
  size_t n;
  bool compressed;
  CHECK(pb::UnframeMessage(framed, &pos, &payload, &n, &compressed));
  CHECK(!compressed && n == msg.size());
  CHECK(memcmp(payload, msg.data(), n) == 0);
  printf("pbwire ok\n");
}

// Hostile-bytes robustness: the response parsers must reject garbage with
// typed errors, never crash or over-read (the wire is untrusted input).
void TestPbWireFuzz() {
  uint64_t state = 0x9E3779B97F4A7C15ull;  // deterministic xorshift
  auto next = [&state]() {
    state ^= state << 13;
    state ^= state >> 7;
    state ^= state << 17;
    return state;
  };
  for (int round = 0; round < 2000; ++round) {
    size_t len = next() % 512;
    std::string buf;
    buf.reserve(len);
    for (size_t i = 0; i < len; ++i) {
      buf.push_back(static_cast<char>(next() & 0xFF));
    }
    // raw reader walk over garbage: must terminate without overrun
    pb::Reader r(buf.data(), buf.size());
    uint32_t field, wt;
    int guard = 0;
    while (r.Next(&field, &wt) && guard++ < 10000) r.Skip(wt);
    CHECK(guard < 10000);
    // frame parser over garbage
    size_t pos = 0;
    const uint8_t* payload;
    size_t payload_size;
    bool compressed;
    guard = 0;
    while (pb::UnframeMessage(buf, &pos, &payload, &payload_size, &compressed) &&
           guard++ < 10000) {
    }
    CHECK(guard < 10000);
  }
  printf("pbwire fuzz ok\n");
}

// Full GRPC client flow over the hand-rolled h2 transport against a live
// GrpcInferenceServer (reference cc_client_test.cc's GRPC instantiation).
void TestGrpcOnline(const std::string& url) {
  std::unique_ptr<InferenceServerGrpcClient> client;
  CHECK_OK(InferenceServerGrpcClient::Create(&client, url));

  bool flag = false;
  CHECK_OK(client->IsServerLive(&flag));
  CHECK(flag);
  CHECK_OK(client->IsServerReady(&flag));
  CHECK(flag);
  CHECK_OK(client->IsModelReady(&flag, "simple"));
  CHECK(flag);

  Json metadata;
  CHECK_OK(client->ServerMetadata(&metadata));
  CHECK(!metadata.At("name").AsString().empty());

  Json model_md;
  CHECK_OK(client->ModelMetadata(&model_md, "simple"));
  CHECK(model_md.At("name").AsString() == "simple");
  CHECK(model_md.At("inputs").size() == 2);
  CHECK(model_md.At("inputs")[0].At("datatype").AsString() == "INT32");

  Json config;
  CHECK_OK(client->ModelConfig(&config, "simple"));
  CHECK(config.At("config").At("name").AsString() == "simple");
  CHECK(config.At("config").At("backend").AsString() == "jax");

  Json index;
  CHECK_OK(client->ModelRepositoryIndex(&index));
  CHECK(index.size() > 0);

  // sync infer: simple sum/diff
  InferInput *in0, *in1;
  CHECK_OK(InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32"));
  CHECK_OK(InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32"));
  int32_t a[16], b[16];
  for (int i = 0; i < 16; ++i) {
    a[i] = i;
    b[i] = 1;
  }
  CHECK_OK(in0->AppendRaw(reinterpret_cast<uint8_t*>(a), sizeof(a)));
  CHECK_OK(in1->AppendRaw(reinterpret_cast<uint8_t*>(b), sizeof(b)));
  InferOptions options("simple");
  options.request_id = "grpc-smoke-1";
  InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, {in0, in1}));
  const uint8_t* buf;
  size_t byte_size;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  CHECK(byte_size == sizeof(a));
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK(sums[i] == a[i] + b[i]);
  CHECK_OK(result->RawData("OUTPUT1", &buf, &byte_size));
  const int32_t* diffs = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK(diffs[i] == a[i] - b[i]);
  std::string id;
  CHECK_OK(result->Id(&id));
  CHECK(id == "grpc-smoke-1");
  delete result;

  // error mapping: unknown model -> typed status string
  InferOptions bad("definitely_missing");
  InferResult* bad_result = nullptr;
  Error err = client->Infer(&bad_result, bad, {in0});
  CHECK(err);
  CHECK(err.Message().find("StatusCode.") != std::string::npos);
  delete bad_result;

  // async infer
  std::mutex mutex;
  std::condition_variable cv;
  int done = 0;
  bool async_ok = true;
  for (int i = 0; i < 8; ++i) {
    CHECK_OK(client->AsyncInfer(
        [&](InferResult* r) {
          const uint8_t* data;
          size_t n;
          if (r->RequestStatus() || r->RawData("OUTPUT0", &data, &n) ||
              n != sizeof(a)) {
            async_ok = false;
          }
          delete r;
          std::lock_guard<std::mutex> lock(mutex);
          ++done;
          cv.notify_one();
        },
        options, {in0, in1}));
  }
  {
    std::unique_lock<std::mutex> lock(mutex);
    CHECK(cv.wait_for(lock, std::chrono::seconds(30), [&] { return done == 8; }));
  }
  CHECK(async_ok);

  // statistics reflect the calls above
  Json stats;
  CHECK_OK(client->ModelInferenceStatistics(&stats, "simple"));
  CHECK(stats.At("model_stats").size() > 0);

  // trace settings round trip, incl. clearing with a null value
  Json trace_update = Json::Object();
  Json level = Json::Array();
  level.Append(Json("TIMESTAMPS"));
  trace_update.Set("trace_level", std::move(level));
  Json trace_resp;
  CHECK_OK(client->UpdateTraceSettings(&trace_resp, "", trace_update));
  CHECK(trace_resp.At("trace_level").size() == 1);
  Json off = Json::Object();
  Json off_level = Json::Array();
  off_level.Append(Json("OFF"));
  off.Set("trace_level", std::move(off_level));
  CHECK_OK(client->UpdateTraceSettings(&trace_resp, "", off));

  // log settings
  Json log_settings;
  CHECK_OK(client->GetLogSettings(&log_settings));
  Json log_update = Json::Object();
  log_update.Set("log_verbose_level", Json(static_cast<int64_t>(2)));
  CHECK_OK(client->UpdateLogSettings(&log_settings, log_update));

  // system shm negotiation (register/status/infer-from-region/unregister)
  const char* shm_key = "/ct_grpc_smoke";
  const size_t shm_size = sizeof(a);
  void* shm_base = nullptr;
  int shm_fd = -1;
  CHECK_OK(CreateSharedMemoryRegion(shm_key, shm_size, &shm_fd));
  CHECK_OK(MapSharedMemory(shm_fd, 0, shm_size, &shm_base));
  memcpy(shm_base, a, sizeof(a));
  CHECK_OK(client->RegisterSystemSharedMemory("grpc_smoke", shm_key, shm_size));
  Json shm_status;
  CHECK_OK(client->SystemSharedMemoryStatus(&shm_status));
  CHECK(shm_status.Has("grpc_smoke"));
  InferInput* shm_in;
  CHECK_OK(InferInput::Create(&shm_in, "INPUT0", {1, 16}, "INT32"));
  CHECK_OK(shm_in->SetSharedMemory("grpc_smoke", shm_size));
  InferOptions id_options("custom_identity_int32");
  InferResult* shm_result = nullptr;
  CHECK_OK(client->Infer(&shm_result, id_options, {shm_in}));
  CHECK_OK(shm_result->RawData("OUTPUT0", &buf, &byte_size));
  CHECK(byte_size == sizeof(a));
  CHECK(memcmp(buf, a, sizeof(a)) == 0);
  delete shm_result;
  CHECK_OK(client->UnregisterSystemSharedMemory("grpc_smoke"));
  UnmapSharedMemory(shm_base, shm_size);
  CloseSharedMemory(shm_fd);
  UnlinkSharedMemoryRegion(shm_key);

  // bi-di streaming: stateful sequence accumulates over the stream
  std::mutex smutex;
  std::condition_variable scv;
  std::vector<int32_t> sums_seen;
  CHECK_OK(client->StartStream([&](InferResult* r, const Error& stream_err) {
    if (!stream_err && r != nullptr) {
      const uint8_t* data;
      size_t n;
      if (!r->RawData("OUTPUT", &data, &n) && n == 4) {
        std::lock_guard<std::mutex> lock(smutex);
        sums_seen.push_back(*reinterpret_cast<const int32_t*>(data));
        scv.notify_one();
      }
    }
    delete r;
  }));
  InferInput* seq_in;
  CHECK_OK(InferInput::Create(&seq_in, "INPUT", {1, 1}, "INT32"));
  int32_t five = 5;
  CHECK_OK(seq_in->AppendRaw(reinterpret_cast<uint8_t*>(&five), 4));
  for (int i = 0; i < 3; ++i) {
    InferOptions seq_options("simple_sequence");
    seq_options.sequence_id = 4242;
    seq_options.sequence_start = (i == 0);
    seq_options.sequence_end = (i == 2);
    CHECK_OK(client->AsyncStreamInfer(seq_options, {seq_in}));
  }
  {
    std::unique_lock<std::mutex> lock(smutex);
    CHECK(scv.wait_for(
        lock, std::chrono::seconds(30), [&] { return sums_seen.size() == 3; }));
  }
  CHECK_OK(client->StopStream());
  CHECK(sums_seen[0] == 5 && sums_seen[1] == 10 && sums_seen[2] == 15);

  // client-side stats accumulated
  InferStat stat = client->ClientInferStat();
  CHECK(stat.completed_request_count >= 10);

  delete in0;
  delete in1;
  delete shm_in;
  delete seq_in;
  printf("grpc online ok (%llu requests)\n",
         static_cast<unsigned long long>(stat.completed_request_count));
}

int main() {
  TestJson();
  TestBase64();
  TestStringsSerialization();
  TestShm();
  TestTpuShm();
  TestOfflineMarshaling();
  TestPbWire();
  TestPbWireFuzz();
  const char* url = getenv("CLIENT_TPU_TEST_URL");
  if (url != nullptr && url[0] != '\0') {
    TestOnline(url);
  } else {
    printf("skip online tests (CLIENT_TPU_TEST_URL unset)\n");
  }
  const char* grpc_url = getenv("CLIENT_TPU_TEST_GRPC_URL");
  if (grpc_url != nullptr && grpc_url[0] != '\0') {
    TestGrpcOnline(grpc_url);
  } else {
    printf("skip grpc online tests (CLIENT_TPU_TEST_GRPC_URL unset)\n");
  }
  printf("PASS\n");
  return 0;
}
