// Offline + online smoke tests for the native library.
// Offline: json/base64/BYTES-serialization/shm/tpu-shm round trips.
// Online (CLIENT_TPU_TEST_URL set, e.g. 127.0.0.1:8000): full client flow
// against a live v2 server — health, metadata, sync Infer, AsyncInfer,
// system + tpu shared-memory inference.
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "client_tpu/base64.h"
#include "client_tpu/common.h"
#include "client_tpu/http_client.h"
#include "client_tpu/json.h"
#include "client_tpu/shm_utils.h"
#include "client_tpu/tpu_shm.h"

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAILED at %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

#define CHECK_OK(expr)                                                  \
  do {                                                                  \
    client_tpu::Error err_ = (expr);                                    \
    if (err_) {                                                         \
      fprintf(stderr, "FAILED at %s:%d: %s\n", __FILE__, __LINE__,      \
              err_.Message().c_str());                                  \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

using namespace client_tpu;

void TestJson() {
  Json parsed;
  std::string error;
  CHECK(Json::Parse(
      R"({"a": 1, "b": [1.5, "x\n", true, null], "c": {"d": -3}})", &parsed,
      &error));
  CHECK(parsed.At("a").AsInt() == 1);
  CHECK(parsed.At("b").size() == 4);
  CHECK(parsed.At("b")[0].AsDouble() == 1.5);
  CHECK(parsed.At("b")[1].AsString() == "x\n");
  CHECK(parsed.At("b")[2].AsBool());
  CHECK(parsed.At("b")[3].is_null());
  CHECK(parsed.At("c").At("d").AsInt() == -3);
  // round trip
  Json again;
  CHECK(Json::Parse(parsed.Dump(), &again, &error));
  CHECK(again.At("c").At("d").AsInt() == -3);
  CHECK(!Json::Parse("{bad", &again, &error));
  printf("ok json\n");
}

void TestBase64() {
  const uint8_t data[] = {0x00, 0x01, 0xFE, 0xFF, 0x7F, 0x80, 0x41};
  std::string encoded = Base64Encode(data, sizeof(data));
  std::vector<uint8_t> decoded;
  CHECK(Base64Decode(encoded, &decoded));
  CHECK(decoded.size() == sizeof(data));
  CHECK(memcmp(decoded.data(), data, sizeof(data)) == 0);
  CHECK(Base64Encode(reinterpret_cast<const uint8_t*>("ab"), 2) == "YWI=");
  printf("ok base64\n");
}

void TestStringsSerialization() {
  std::vector<std::string> input = {"hello", "", std::string("\x00\x01", 2)};
  std::string serialized;
  SerializeStrings(input, &serialized);
  CHECK(serialized.size() == 4 * 3 + 5 + 0 + 2);
  std::vector<std::string> output;
  CHECK_OK(DeserializeStrings(
      reinterpret_cast<const uint8_t*>(serialized.data()), serialized.size(),
      &output));
  CHECK(output == input);
  std::vector<std::string> bad;
  CHECK(DeserializeStrings(
      reinterpret_cast<const uint8_t*>("\x05\x00\x00\x00"), 4, &bad));
  printf("ok strings\n");
}

void TestShm() {
  const char* key = "/ctpu_native_smoke";
  int fd = -1;
  CHECK_OK(CreateSharedMemoryRegion(key, 256, &fd));
  void* addr = nullptr;
  CHECK_OK(MapSharedMemory(fd, 0, 256, &addr));
  memcpy(addr, "native", 6);

  int fd2 = -1;
  CHECK_OK(OpenSharedMemoryRegion(key, &fd2));
  void* addr2 = nullptr;
  CHECK_OK(MapSharedMemory(fd2, 0, 256, &addr2));
  CHECK(memcmp(addr2, "native", 6) == 0);

  CHECK_OK(UnmapSharedMemory(addr, 256));
  CHECK_OK(UnmapSharedMemory(addr2, 256));
  CHECK_OK(CloseSharedMemory(fd));
  CHECK_OK(CloseSharedMemory(fd2));
  CHECK_OK(UnlinkSharedMemoryRegion(key));
  printf("ok shm\n");
}

void TestTpuShm() {
  TpuShmRegion* region = nullptr;
  CHECK_OK(TpuShmRegion::Create(&region, "native_region", 128));
  int32_t values[4] = {1, 2, 3, 4};
  CHECK_OK(region->Write(values, sizeof(values)));
  // attach through the serialized handle (the cross-process path)
  std::string handle = region->RawHandle();
  TpuShmRegion* attached = nullptr;
  CHECK_OK(TpuShmRegion::Attach(&attached, handle));
  int32_t readback[4] = {};
  CHECK_OK(attached->Read(readback, sizeof(readback)));
  CHECK(memcmp(values, readback, sizeof(values)) == 0);
  CHECK(attached->ByteSize() == 128);
  // bounds
  CHECK(region->Write(values, sizeof(values), 126));
  delete attached;
  delete region;
  printf("ok tpu_shm\n");
}

void TestOnline(const std::string& url) {
  std::unique_ptr<InferenceServerHttpClient> client;
  CHECK_OK(InferenceServerHttpClient::Create(&client, url));

  bool live = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK(live);
  bool ready = false;
  CHECK_OK(client->IsModelReady(&ready, "simple"));
  CHECK(ready);

  Json metadata;
  CHECK_OK(client->ServerMetadata(&metadata));
  CHECK(!metadata.At("name").AsString().empty());
  Json model_md;
  CHECK_OK(client->ModelMetadata(&model_md, "simple"));
  CHECK(model_md.At("inputs").size() == 2);

  // sync infer: INT32 sum/diff
  int32_t input0[16], input1[16];
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 1;
  }
  InferInput* in0;
  InferInput* in1;
  InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  CHECK_OK(in0->AppendRaw(reinterpret_cast<uint8_t*>(input0), sizeof(input0)));
  CHECK_OK(in1->AppendRaw(reinterpret_cast<uint8_t*>(input1), sizeof(input1)));

  InferOptions options("simple");
  options.request_id = "native-1";
  InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, {in0, in1}));
  const uint8_t* buf;
  size_t byte_size;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  CHECK(byte_size == sizeof(input0));
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK(sums[i] == input0[i] + input1[i]);
  std::string id;
  CHECK_OK(result->Id(&id));
  CHECK(id == "native-1");
  delete result;
  printf("ok online sync infer\n");

  // async infer
  std::mutex mu;
  std::condition_variable cv;
  int remaining = 4;
  bool all_ok = true;
  for (int r = 0; r < 4; ++r) {
    CHECK_OK(client->AsyncInfer(
        [&](InferResult* async_result) {
          const uint8_t* abuf;
          size_t asize;
          bool ok = async_result->RequestStatus().IsOk() &&
                    async_result->RawData("OUTPUT1", &abuf, &asize).IsOk();
          if (ok) {
            const int32_t* diffs = reinterpret_cast<const int32_t*>(abuf);
            for (int i = 0; i < 16; ++i) {
              ok = ok && diffs[i] == input0[i] - input1[i];
            }
          }
          delete async_result;
          std::lock_guard<std::mutex> lock(mu);
          all_ok = all_ok && ok;
          if (--remaining == 0) cv.notify_one();
        },
        options, {in0, in1}));
  }
  {
    std::unique_lock<std::mutex> lock(mu);
    CHECK(cv.wait_for(lock, std::chrono::seconds(30), [&] {
      return remaining == 0;
    }));
  }
  CHECK(all_ok);
  printf("ok online async infer\n");

  // JSON-mode output (binary_data=false): readable through the same accessor
  InferRequestedOutput* json_out;
  InferRequestedOutput::Create(&json_out, "OUTPUT0");
  json_out->SetBinaryData(false);
  InferResult* json_result = nullptr;
  CHECK_OK(client->Infer(&json_result, options, {in0, in1}, {json_out}));
  const uint8_t* jbuf;
  size_t jsize;
  CHECK_OK(json_result->RawData("OUTPUT0", &jbuf, &jsize));
  CHECK(jsize == sizeof(input0));
  const int32_t* jsums = reinterpret_cast<const int32_t*>(jbuf);
  for (int i = 0; i < 16; ++i) CHECK(jsums[i] == input0[i] + input1[i]);
  delete json_result;
  delete json_out;
  printf("ok online json-mode output\n");

  // tpu shared-memory inference: inputs and outputs via regions
  TpuShmRegion* rin = nullptr;
  TpuShmRegion* rout = nullptr;
  CHECK_OK(TpuShmRegion::Create(&rin, "native_in", 128));
  CHECK_OK(TpuShmRegion::Create(&rout, "native_out", 128));
  CHECK_OK(rin->Write(input0, 64, 0));
  CHECK_OK(rin->Write(input1, 64, 64));
  CHECK_OK(client->RegisterTpuSharedMemory("native_in", rin->RawHandle(), 0, 128));
  CHECK_OK(
      client->RegisterTpuSharedMemory("native_out", rout->RawHandle(), 0, 128));

  in0->SetSharedMemory("native_in", 64, 0);
  in1->SetSharedMemory("native_in", 64, 64);
  InferRequestedOutput* out0;
  InferRequestedOutput* out1;
  InferRequestedOutput::Create(&out0, "OUTPUT0");
  InferRequestedOutput::Create(&out1, "OUTPUT1");
  out0->SetSharedMemory("native_out", 64, 0);
  out1->SetSharedMemory("native_out", 64, 64);

  InferResult* shm_result = nullptr;
  CHECK_OK(client->Infer(&shm_result, options, {in0, in1}, {out0, out1}));
  delete shm_result;
  int32_t shm_sums[16], shm_diffs[16];
  CHECK_OK(rout->Read(shm_sums, 64, 0));
  CHECK_OK(rout->Read(shm_diffs, 64, 64));
  for (int i = 0; i < 16; ++i) {
    CHECK(shm_sums[i] == input0[i] + input1[i]);
    CHECK(shm_diffs[i] == input0[i] - input1[i]);
  }
  Json status;
  CHECK_OK(client->TpuSharedMemoryStatus(&status));
  CHECK(status.size() == 2);
  CHECK_OK(client->UnregisterTpuSharedMemory(""));
  delete rin;
  delete rout;
  printf("ok online tpu shm infer\n");

  // InferMulti / AsyncInferMulti with option broadcasting
  in0->Reset();
  in1->Reset();
  in0->AppendRaw(reinterpret_cast<uint8_t*>(input0), sizeof(input0));
  in1->AppendRaw(reinterpret_cast<uint8_t*>(input1), sizeof(input1));
  std::vector<InferResult*> multi_results;
  CHECK_OK(client->InferMulti(
      &multi_results, {options}, {{in0, in1}, {in0, in1}, {in0, in1}}));
  CHECK(multi_results.size() == 3);
  for (auto* r : multi_results) {
    const uint8_t* mbuf;
    size_t msize;
    CHECK_OK(r->RawData("OUTPUT0", &mbuf, &msize));
    CHECK(reinterpret_cast<const int32_t*>(mbuf)[3] ==
          input0[3] + input1[3]);
    delete r;
  }
  {
    std::mutex mmu;
    std::condition_variable mcv;
    bool multi_done = false;
    CHECK_OK(client->AsyncInferMulti(
        [&](std::vector<InferResult*> async_results) {
          bool ok = async_results.size() == 2;
          for (auto* r : async_results) {
            ok = ok && r->RequestStatus().IsOk();
            delete r;
          }
          std::lock_guard<std::mutex> lock(mmu);
          multi_done = ok;
          mcv.notify_one();
        },
        {options}, {{in0, in1}, {in0, in1}}));
    std::unique_lock<std::mutex> lock(mmu);
    CHECK(mcv.wait_for(lock, std::chrono::seconds(30), [&] {
      return multi_done;
    }));
  }
  printf("ok online infer multi\n");

  // stats reflect the traffic
  InferStat stat = client->ClientInferStat();
  CHECK(stat.completed_request_count >= 6);
  Json server_stats;
  CHECK_OK(client->ModelInferenceStatistics(&server_stats, "simple"));
  CHECK(server_stats.At("model_stats").size() == 1);

  delete in0;
  delete in1;
  delete out0;
  delete out1;
  printf("ok online stats\n");
}

void TestOfflineMarshaling() {
  // GenerateRequestBody/ParseResponseBody round trip with no server
  int32_t values[4] = {5, 6, 7, 8};
  InferInput* input = nullptr;
  InferInput::Create(&input, "IN", {4}, "INT32");
  input->AppendRaw(reinterpret_cast<uint8_t*>(values), sizeof(values));
  InferOptions options("m");
  std::string body;
  size_t header_length = 0;
  CHECK_OK(InferenceServerHttpClient::GenerateRequestBody(
      &body, &header_length, options, {input}));
  CHECK(header_length > 0 && body.size() == header_length + sizeof(values));
  Json header;
  std::string perr;
  CHECK(Json::Parse(body.substr(0, header_length), &header, &perr));
  CHECK(header.At("inputs")[0].At("name").AsString() == "IN");
  delete input;

  // a response body built by hand parses back through the public API
  Json resp = Json::Object();
  Json out = Json::Object();
  out.Set("name", Json("OUT"));
  out.Set("datatype", Json("INT32"));
  Json shape = Json::Array();
  shape.Append(Json(static_cast<int64_t>(4)));
  out.Set("shape", std::move(shape));
  Json params = Json::Object();
  params.Set("binary_data_size", Json(static_cast<int64_t>(16)));
  out.Set("parameters", std::move(params));
  Json outs = Json::Array();
  outs.Append(std::move(out));
  resp.Set("outputs", std::move(outs));
  std::string resp_header = resp.Dump();
  std::string resp_body = resp_header;
  resp_body.append(reinterpret_cast<char*>(values), sizeof(values));
  InferResult* result = nullptr;
  CHECK_OK(InferenceServerHttpClient::ParseResponseBody(
      &result, std::move(resp_body), resp_header.size()));
  const uint8_t* buf;
  size_t size;
  CHECK_OK(result->RawData("OUT", &buf, &size));
  CHECK(size == 16 && memcmp(buf, values, 16) == 0);
  delete result;
  printf("ok offline marshaling\n");
}

int main() {
  TestJson();
  TestBase64();
  TestStringsSerialization();
  TestShm();
  TestTpuShm();
  TestOfflineMarshaling();
  const char* url = getenv("CLIENT_TPU_TEST_URL");
  if (url != nullptr && url[0] != '\0') {
    TestOnline(url);
  } else {
    printf("skip online tests (CLIENT_TPU_TEST_URL unset)\n");
  }
  printf("PASS\n");
  return 0;
}
