// Leak harness for the native clients (reference src/c++/tests/
// memory_leak_test.cc:324 — loops inferences for external leak tooling).
// The image has no valgrind, so this binary is built with
// -fsanitize=address: LeakSanitizer reports anything still reachable-lost
// at exit and fails the process. Exercises full lifecycle churn — clients,
// inputs, results, async callbacks, streams — not just the steady state.
//
// env: CLIENT_TPU_TEST_URL (HTTP server), CLIENT_TPU_TEST_GRPC_URL (GRPC).
// argv[1]: repetitions (default 100).
#include <unistd.h>

#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#include "client_tpu/grpc_client.h"
#include "client_tpu/http_client.h"

using namespace client_tpu;

#define CHECK_OK(expr)                                                  \
  do {                                                                  \
    Error err_ = (expr);                                                \
    if (err_) {                                                         \
      fprintf(stderr, "FAILED at %s:%d: %s\n", __FILE__, __LINE__,      \
              err_.Message().c_str());                                  \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

static std::vector<int32_t> MakeData(size_t n) {
  std::vector<int32_t> data(n);
  for (size_t i = 0; i < n; ++i) data[i] = static_cast<int32_t>(i);
  return data;
}

static void HttpChurn(const char* url, int reps) {
  auto data = MakeData(1 << 14);
  for (int i = 0; i < reps; ++i) {
    std::unique_ptr<InferenceServerHttpClient> client;
    CHECK_OK(InferenceServerHttpClient::Create(&client, url));
    InferInput* input;
    CHECK_OK(InferInput::Create(
        &input, "INPUT0", {1, (int64_t)data.size()}, "INT32"));
    CHECK_OK(input->AppendRaw(
        reinterpret_cast<uint8_t*>(data.data()), data.size() * 4));
    InferOptions options("custom_identity_int32");
    InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {input}));
    const uint8_t* buf;
    size_t n;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &n));
    if (n != data.size() * 4) exit(2);
    delete result;
    // async on the same client (worker thread spin-up/drain)
    std::mutex m;
    std::condition_variable cv;
    bool done = false;
    bool async_ok = true;
    CHECK_OK(client->AsyncInfer(
        [&](InferResult* r) {
          if (r == nullptr || r->RequestStatus()) async_ok = false;
          delete r;
          std::lock_guard<std::mutex> lock(m);
          done = true;
          cv.notify_one();
        },
        options, {input}));
    {
      std::unique_lock<std::mutex> lock(m);
      cv.wait(lock, [&] { return done; });
    }
    if (!async_ok) {
      fprintf(stderr, "async infer returned an error result\n");
      exit(3);
    }
    delete input;
  }
}

static void GrpcChurn(const char* url, int reps) {
  auto data = MakeData(1 << 14);
  for (int i = 0; i < reps; ++i) {
    std::unique_ptr<InferenceServerGrpcClient> client;
    CHECK_OK(InferenceServerGrpcClient::Create(&client, url));
    InferInput* input;
    CHECK_OK(InferInput::Create(
        &input, "INPUT0", {1, (int64_t)data.size()}, "INT32"));
    CHECK_OK(input->AppendRaw(
        reinterpret_cast<uint8_t*>(data.data()), data.size() * 4));
    InferOptions options("custom_identity_int32");
    InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {input}));
    delete result;
    // one short-lived stream per few reps: open/send/receive/close churn
    if (i % 4 == 0) {
      std::mutex m;
      std::condition_variable cv;
      int got = 0;
      bool stream_ok = true;
      CHECK_OK(client->StartStream([&](InferResult* r, const Error& e) {
        if (e || r == nullptr || r->RequestStatus()) stream_ok = false;
        delete r;
        std::lock_guard<std::mutex> lock(m);
        ++got;
        cv.notify_one();
      }));
      CHECK_OK(client->AsyncStreamInfer(options, {input}));
      {
        std::unique_lock<std::mutex> lock(m);
        cv.wait(lock, [&] { return got == 1; });
      }
      CHECK_OK(client->StopStream());
      if (!stream_ok) {
        fprintf(stderr, "stream returned an error result\n");
        exit(4);
      }
    }
    delete input;
  }
}

int main(int argc, char** argv) {
  int reps = argc > 1 ? atoi(argv[1]) : 100;
  const char* http_url = getenv("CLIENT_TPU_TEST_URL");
  const char* grpc_url = getenv("CLIENT_TPU_TEST_GRPC_URL");
  bool any = false;
  if (http_url != nullptr && http_url[0] != '\0') {
    HttpChurn(http_url, reps);
    printf("http churn ok (%d reps)\n", reps);
    any = true;
  }
  if (grpc_url != nullptr && grpc_url[0] != '\0') {
    GrpcChurn(grpc_url, reps);
    printf("grpc churn ok (%d reps)\n", reps);
    any = true;
  }
  if (!any) {
    printf("no server urls set; nothing exercised\n");
    return 0;
  }
  printf("PASS leak_test\n");
  return 0;
}
