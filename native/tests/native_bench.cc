// Native-client latency bench: the tpu-shm control-message hot path.
// Usage: CLIENT_TPU_TEST_URL=host:port native_bench [n_elems] [iters]
// Prints one JSON line with p50/p99 for wire vs tpu-shm data planes.
#include <malloc.h>

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/http_client.h"
#include "client_tpu/tpu_shm.h"

using namespace client_tpu;

static double Percentile(std::vector<double>& v, double q) {
  std::sort(v.begin(), v.end());
  size_t idx = std::min(
      static_cast<size_t>(v.size() * q), v.size() - 1);
  return v[idx];
}

int main(int argc, char** argv) {
  const char* url = getenv("CLIENT_TPU_TEST_URL");
  if (url == nullptr || url[0] == '\0') {
    fprintf(stderr, "CLIENT_TPU_TEST_URL unset\n");
    return 2;
  }
  size_t n_elems = argc > 1 ? strtoull(argv[1], nullptr, 10) : (1u << 20);
  int iters = argc > 2 ? atoi(argv[2]) : 50;
  size_t nbytes = n_elems * sizeof(float);
  // CLIENT_TPU_BENCH_TRIM_EVERY=N: malloc_trim(0) every N iterations, so an
  // external RSS sampler (the soak tier) reads reachable heap rather than
  // glibc's free-but-unreturned retention — the same post-trim protocol the
  // python soak uses; a true leak still shows as a positive trimmed slope
  long trim_every = 0;
  if (const char* te = getenv("CLIENT_TPU_BENCH_TRIM_EVERY")) {
    trim_every = atol(te);
  }

  std::unique_ptr<InferenceServerHttpClient> client;
  if (InferenceServerHttpClient::Create(&client, url)) return 1;

  std::vector<float> data(n_elems);
  for (size_t i = 0; i < n_elems; ++i) data[i] = static_cast<float>(i % 977);

  InferOptions options("identity_fp32");
  auto run = [&](bool shm, std::vector<double>* times) -> Error {
    InferInput* input = nullptr;
    InferInput::Create(
        &input, "INPUT0", {1, static_cast<int64_t>(n_elems)}, "FP32");
    std::unique_ptr<InferInput> input_guard(input);
    TpuShmRegion* rin = nullptr;
    TpuShmRegion* rout = nullptr;
    InferRequestedOutput* out0 = nullptr;
    InferRequestedOutput::Create(&out0, "OUTPUT0");
    std::unique_ptr<InferRequestedOutput> out_guard(out0);
    std::vector<const InferRequestedOutput*> outputs;
    if (shm) {
      Error err = TpuShmRegion::Create(&rin, "nb_in", nbytes);
      if (err) return err;
      err = TpuShmRegion::Create(&rout, "nb_out", nbytes);
      if (err) return err;
      if ((err = client->RegisterTpuSharedMemory(
               "nb_in", rin->RawHandle(), 0, nbytes)))
        return err;
      if ((err = client->RegisterTpuSharedMemory(
               "nb_out", rout->RawHandle(), 0, nbytes)))
        return err;
      input->SetSharedMemory("nb_in", nbytes);
      out0->SetSharedMemory("nb_out", nbytes);
      outputs.push_back(out0);
    }
    std::vector<float> readback(n_elems);
    // soak runs pass huge iter counts: cap retained samples and reserve
    // upfront — an unboundedly growing vector whose doubling reallocations
    // interleave with the per-request transient buffers ratchets the glibc
    // heap high-water (the r03 soak's "native leak": LSan-clean, in-use
    // heap flat, yet RSS climbing ~400 KB/min on a quiet machine)
    constexpr size_t kMaxSamples = 1u << 18;
    times->reserve(std::min(static_cast<size_t>(iters), kMaxSamples));
    for (int i = 0; i < iters + 5; ++i) {
      auto t0 = std::chrono::steady_clock::now();
      Error err;
      if (shm) {
        rin->Write(data.data(), nbytes);
        InferResult* result = nullptr;
        err = client->Infer(&result, options, {input}, outputs);
        delete result;
        if (!err) rout->Read(readback.data(), nbytes);
      } else {
        input->Reset();
        input->AppendRaw(
            reinterpret_cast<const uint8_t*>(data.data()), nbytes);
        InferResult* result = nullptr;
        err = client->Infer(&result, options, {input});
        if (!err) {
          const uint8_t* buf;
          size_t size;
          result->RawData("OUTPUT0", &buf, &size);
          memcpy(readback.data(), buf, std::min(size, nbytes));
        }
        delete result;
      }
      if (err) {
        fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
        return err;
      }
      if (readback[1] != data[1]) {
        fprintf(stderr, "wrong results\n");
        return Error("wrong results");
      }
      auto dt = std::chrono::duration<double, std::milli>(
                    std::chrono::steady_clock::now() - t0)
                    .count();
      if (i >= 5 && times->size() < kMaxSamples) times->push_back(dt);
      if (trim_every > 0 && i % trim_every == 0) malloc_trim(0);
    }
    if (shm) {
      client->UnregisterTpuSharedMemory("");
      delete rin;
      delete rout;
    }
    return Error::Success();
  };

  std::vector<double> wire_times, shm_times;
  if (run(false, &wire_times)) return 1;
  if (run(true, &shm_times)) return 1;

  printf(
      "{\"metric\": \"native C++ client identity %.1fMiB p50\", "
      "\"wire_p50_ms\": %.3f, \"wire_p99_ms\": %.3f, "
      "\"tpu_shm_p50_ms\": %.3f, \"tpu_shm_p99_ms\": %.3f, "
      "\"speedup\": %.2f}\n",
      nbytes / 1048576.0, Percentile(wire_times, 0.5),
      Percentile(wire_times, 0.99), Percentile(shm_times, 0.5),
      Percentile(shm_times, 0.99),
      Percentile(wire_times, 0.5) / Percentile(shm_times, 0.5));
  return 0;
}
