// Dual-protocol typed test suite: ONE suite body instantiated for both the
// HTTP and GRPC native clients, so API-surface symmetry is guaranteed by
// construction rather than by convention. Role parity with the reference's
// INSTANTIATE_TYPED_TEST_SUITE_P(GRPC|HTTP, ClientTest, ...)
// (/root/reference/src/c++/tests/cc_client_test.cc:2183-2184): the template
// only compiles if both clients expose identical signatures for the entire
// tested subset — a divergence is a build error, not a missed review.
//
// Driven by tests/test_native.py against the live in-process server:
//   CLIENT_TPU_TEST_URL=host:port CLIENT_TPU_TEST_GRPC_URL=host:port \
//     native/build/dual_client_test

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"
#include "client_tpu/http_client.h"
#include "client_tpu/shm_utils.h"

namespace tc = client_tpu;

static int g_failures = 0;

#define CHECK_OK(X, MSG)                                              \
  do {                                                                \
    const tc::Error e_ = (X);                                         \
    if (!e_.IsOk()) {                                                 \
      std::fprintf(                                                   \
          stderr, "FAIL %s: %s: %s\n", suite, (MSG),                  \
          e_.Message().c_str());                                      \
      ++g_failures;                                                   \
      return;                                                         \
    }                                                                 \
  } while (false)

#define CHECK_TRUE(X, MSG)                                       \
  do {                                                           \
    if (!(X)) {                                                  \
      std::fprintf(stderr, "FAIL %s: %s\n", suite, (MSG));       \
      ++g_failures;                                              \
      return;                                                    \
    }                                                            \
  } while (false)

namespace {

tc::Error
MakeInt32Input(
    std::unique_ptr<tc::InferInput>* out, const std::string& name,
    const std::vector<int32_t>& data)
{
  tc::InferInput* raw = nullptr;
  const tc::Error err = tc::InferInput::Create(
      &raw, name, {1, static_cast<int64_t>(data.size())}, "INT32");
  if (!err.IsOk()) {
    return err;
  }
  out->reset(raw);
  return raw->AppendRaw(
      reinterpret_cast<const uint8_t*>(data.data()),
      data.size() * sizeof(int32_t));
}

// The typed suite: every test is written once against ClientT. Both
// clients must expose the identical subset or this translation unit does
// not compile.
template <typename ClientT>
void
RunSuite(const char* suite, const std::string& url)
{
  std::unique_ptr<ClientT> client;
  CHECK_OK(ClientT::Create(&client, url), "Create");

  // -- health + admin surface ------------------------------------------
  bool live = false;
  CHECK_OK(client->IsServerLive(&live), "IsServerLive");
  CHECK_TRUE(live, "server not live");
  bool ready = false;
  CHECK_OK(client->IsServerReady(&ready), "IsServerReady");
  CHECK_TRUE(ready, "server not ready");
  bool model_ready = false;
  CHECK_OK(client->IsModelReady(&model_ready, "simple"), "IsModelReady");
  CHECK_TRUE(model_ready, "simple not ready");

  tc::Json server_meta;
  CHECK_OK(client->ServerMetadata(&server_meta), "ServerMetadata");
  tc::Json model_meta;
  CHECK_OK(client->ModelMetadata(&model_meta, "simple"), "ModelMetadata");
  tc::Json config;
  CHECK_OK(client->ModelConfig(&config, "simple"), "ModelConfig");
  tc::Json index;
  CHECK_OK(client->ModelRepositoryIndex(&index), "ModelRepositoryIndex");
  tc::Json trace;
  CHECK_OK(client->GetTraceSettings(&trace), "GetTraceSettings");
  tc::Json logs;
  CHECK_OK(client->GetLogSettings(&logs), "GetLogSettings");

  // -- sync infer ------------------------------------------------------
  std::vector<int32_t> in0(16), in1(16);
  for (int i = 0; i < 16; ++i) {
    in0[i] = i;
    in1[i] = 2 * i;
  }
  std::unique_ptr<tc::InferInput> input0, input1;
  CHECK_OK(MakeInt32Input(&input0, "INPUT0", in0), "INPUT0");
  CHECK_OK(MakeInt32Input(&input1, "INPUT1", in1), "INPUT1");
  tc::InferOptions options("simple");

  tc::InferResult* result_raw = nullptr;
  CHECK_OK(
      client->Infer(&result_raw, options, {input0.get(), input1.get()}),
      "Infer");
  std::unique_ptr<tc::InferResult> result(result_raw);
  CHECK_OK(result->RequestStatus(), "Infer status");
  const uint8_t* buf = nullptr;
  size_t nbytes = 0;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &nbytes), "OUTPUT0 data");
  CHECK_TRUE(nbytes == 16 * sizeof(int32_t), "OUTPUT0 size");
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    CHECK_TRUE(sums[i] == in0[i] + in1[i], "OUTPUT0 values");
  }

  // -- error surface: unknown model is a typed error, same on both ------
  tc::InferResult* bad_raw = nullptr;
  tc::InferOptions bad_options("no_such_model");
  const tc::Error bad =
      client->Infer(&bad_raw, bad_options, {input0.get(), input1.get()});
  if (bad.IsOk()) {
    // some transports surface the failure on the result status instead
    std::unique_ptr<tc::InferResult> bad_result(bad_raw);
    CHECK_TRUE(
        !bad_result->RequestStatus().IsOk(),
        "unknown model must fail (result status)");
  }

  // -- BYTES/string tensors both directions (reference cc_client_test.cc
  // string cases: AppendFromString on send, StringData on receive) -------
  {
    std::vector<std::string> a_strs, b_strs;
    for (int i = 0; i < 16; ++i) {
      a_strs.push_back(std::to_string(10 + i));
      b_strs.push_back(std::to_string(2 * i));
    }
    tc::InferInput* sa_raw = nullptr;
    CHECK_OK(
        tc::InferInput::Create(&sa_raw, "INPUT0", {1, 16}, "BYTES"),
        "string INPUT0");
    std::unique_ptr<tc::InferInput> sa(sa_raw);
    CHECK_OK(sa->AppendFromString(a_strs), "AppendFromString INPUT0");
    tc::InferInput* sb_raw = nullptr;
    CHECK_OK(
        tc::InferInput::Create(&sb_raw, "INPUT1", {1, 16}, "BYTES"),
        "string INPUT1");
    std::unique_ptr<tc::InferInput> sb(sb_raw);
    CHECK_OK(sb->AppendFromString(b_strs), "AppendFromString INPUT1");

    tc::InferOptions str_options("simple_string");
    tc::InferResult* str_raw = nullptr;
    CHECK_OK(
        client->Infer(&str_raw, str_options, {sa.get(), sb.get()}),
        "string Infer");
    std::unique_ptr<tc::InferResult> str_result(str_raw);
    CHECK_OK(str_result->RequestStatus(), "string Infer status");
    std::vector<std::string> sums_s, diffs_s;
    CHECK_OK(str_result->StringData("OUTPUT0", &sums_s), "StringData OUT0");
    CHECK_OK(str_result->StringData("OUTPUT1", &diffs_s), "StringData OUT1");
    CHECK_TRUE(
        sums_s.size() == 16 && diffs_s.size() == 16, "string output count");
    for (int i = 0; i < 16; ++i) {
      CHECK_TRUE(
          sums_s[i] == std::to_string(10 + i + 2 * i), "string sums");
      CHECK_TRUE(
          diffs_s[i] == std::to_string(10 + i - 2 * i), "string diffs");
    }
  }

  // -- requested-output subset (reference cc_client_test.cc:300-420:
  // explicit outputs restrict the response to exactly that set) ---------
  std::unique_ptr<tc::InferRequestedOutput> want1;
  {
    tc::InferRequestedOutput* raw = nullptr;
    CHECK_OK(
        tc::InferRequestedOutput::Create(&raw, "OUTPUT1"),
        "InferRequestedOutput::Create");
    want1.reset(raw);
  }
  tc::InferResult* sub_raw = nullptr;
  CHECK_OK(
      client->Infer(
          &sub_raw, options, {input0.get(), input1.get()}, {want1.get()}),
      "Infer subset");
  std::unique_ptr<tc::InferResult> sub(sub_raw);
  CHECK_OK(sub->RequestStatus(), "Infer subset status");
  std::vector<std::string> sub_names;
  CHECK_OK(sub->OutputNames(&sub_names), "subset OutputNames");
  CHECK_TRUE(
      sub_names.size() == 1 && sub_names[0] == "OUTPUT1",
      "subset must contain exactly OUTPUT1");
  const uint8_t* diff_buf = nullptr;
  size_t diff_nbytes = 0;
  CHECK_OK(sub->RawData("OUTPUT1", &diff_buf, &diff_nbytes), "OUTPUT1 data");
  CHECK_TRUE(diff_nbytes == 16 * sizeof(int32_t), "OUTPUT1 size");
  const int32_t* diffs = reinterpret_cast<const int32_t*>(diff_buf);
  for (int i = 0; i < 16; ++i) {
    CHECK_TRUE(diffs[i] == in0[i] - in1[i], "OUTPUT1 values");
  }
  const uint8_t* absent_buf = nullptr;
  size_t absent_nbytes = 0;
  CHECK_TRUE(
      !sub->RawData("OUTPUT0", &absent_buf, &absent_nbytes).IsOk(),
      "unrequested OUTPUT0 must not be present");

  // -- request_id roundtrip --------------------------------------------
  {
    tc::InferOptions id_options("simple");
    id_options.request_id = "dual-42";
    tc::InferResult* id_raw = nullptr;
    CHECK_OK(
        client->Infer(&id_raw, id_options, {input0.get(), input1.get()}),
        "Infer with request_id");
    std::unique_ptr<tc::InferResult> id_result(id_raw);
    CHECK_OK(id_result->RequestStatus(), "request_id status");
    std::string id;
    CHECK_OK(id_result->Id(&id), "result Id");
    CHECK_TRUE(id == "dual-42", "request_id must round-trip");
  }

  // -- shape mismatch is a typed error, not a crash --------------------
  {
    std::unique_ptr<tc::InferInput> short_input;
    CHECK_OK(
        MakeInt32Input(&short_input, "INPUT0", in0), "mismatch input");
    // 16 int32 elements but a declared shape of [1, 8]: the server must
    // reject the request and the client must surface it as Error/status.
    CHECK_OK(short_input->SetShape({1, 8}), "SetShape");
    tc::InferResult* mm_raw = nullptr;
    const tc::Error mm =
        client->Infer(&mm_raw, options, {short_input.get(), input1.get()});
    if (mm.IsOk()) {
      std::unique_ptr<tc::InferResult> mm_result(mm_raw);
      CHECK_TRUE(
          !mm_result->RequestStatus().IsOk(),
          "shape/body mismatch must fail (result status)");
    }
  }

  // -- InferMulti with option broadcasting -----------------------------
  std::vector<tc::InferResult*> multi_raw;
  CHECK_OK(
      client->InferMulti(
          &multi_raw, {options},
          {{input0.get(), input1.get()}, {input0.get(), input1.get()}}),
      "InferMulti");
  CHECK_TRUE(multi_raw.size() == 2, "InferMulti count");
  for (tc::InferResult* r : multi_raw) {
    std::unique_ptr<tc::InferResult> owned(r);
    CHECK_OK(owned->RequestStatus(), "InferMulti status");
  }

  // -- InferMulti broadcast-mismatch is a typed client-side error ------
  // (2 options for 3 requests is neither broadcast-1 nor match-N)
  {
    std::vector<tc::InferResult*> bad_multi;
    const std::vector<tc::InferInput*> req = {input0.get(), input1.get()};
    const tc::Error mism = client->InferMulti(
        &bad_multi, {options, options}, {req, req, req});
    for (tc::InferResult* r : bad_multi) {
      delete r;
    }
    CHECK_TRUE(!mism.IsOk(), "InferMulti options/requests size mismatch");
  }

  // -- AsyncInfer ------------------------------------------------------
  std::mutex mu;
  std::condition_variable cv;
  bool async_done = false;
  tc::Error async_status("callback never ran");
  CHECK_OK(
      client->AsyncInfer(
          [&](tc::InferResult* r) {
            std::unique_ptr<tc::InferResult> owned(r);
            std::lock_guard<std::mutex> lock(mu);
            async_status = owned->RequestStatus();
            async_done = true;
            cv.notify_one();
          },
          options, {input0.get(), input1.get()}),
      "AsyncInfer");
  {
    std::unique_lock<std::mutex> lock(mu);
    CHECK_TRUE(
        cv.wait_for(
            lock, std::chrono::seconds(30), [&] { return async_done; }),
        "AsyncInfer timeout");
  }
  CHECK_OK(async_status, "AsyncInfer result status");

  // -- system shm lifecycle (register/status/unregister) ---------------
  // POSIX shm names must not contain an interior '/'; sanitize the suite
  // tag ("HTTP/ClientTest") before splicing it into the key.
  std::string suite_tag(suite);
  for (char& c : suite_tag) {
    if (c == '/') {
      c = '_';
    }
  }
  const std::string key = std::string("/dual_suite_") + suite_tag;
  (void)tc::UnlinkSharedMemoryRegion(key);
  int fd = -1;
  CHECK_OK(tc::CreateSharedMemoryRegion(key, 256, &fd), "shm create");
  CHECK_OK(
      client->RegisterSystemSharedMemory("dual_region", key, 256),
      "RegisterSystemSharedMemory");
  tc::Json shm_status;
  CHECK_OK(
      client->SystemSharedMemoryStatus(&shm_status),
      "SystemSharedMemoryStatus");
  CHECK_OK(
      client->UnregisterSystemSharedMemory("dual_region"),
      "UnregisterSystemSharedMemory");
  CHECK_OK(tc::CloseSharedMemory(fd), "shm close");
  CHECK_OK(tc::UnlinkSharedMemoryRegion(key), "shm unlink");

  // -- model control: unload -> not ready -> load -> serves again ------
  // (reference cc_client_test LoadModel/UnloadModel coverage; uses a
  // model no other section touches so suite order never matters)
  {
    CHECK_OK(client->UnloadModel("identity_bf16"), "UnloadModel");
    bool bf16_ready = true;
    CHECK_OK(
        client->IsModelReady(&bf16_ready, "identity_bf16"),
        "IsModelReady after unload");
    CHECK_TRUE(!bf16_ready, "identity_bf16 must be unloaded");
    CHECK_OK(client->LoadModel("identity_bf16"), "LoadModel");
    bf16_ready = false;
    CHECK_OK(
        client->IsModelReady(&bf16_ready, "identity_bf16"),
        "IsModelReady after load");
    CHECK_TRUE(bf16_ready, "identity_bf16 must be ready again");
  }

  // -- statistics ------------------------------------------------------
  tc::Json stats;
  CHECK_OK(
      client->ModelInferenceStatistics(&stats, "simple"),
      "ModelInferenceStatistics");

  std::printf("PASS %s (%s)\n", suite, url.c_str());
}

}  // namespace

int
main()
{
  const char* http_url = std::getenv("CLIENT_TPU_TEST_URL");
  const char* grpc_url = std::getenv("CLIENT_TPU_TEST_GRPC_URL");
  bool ran = false;
  if (http_url != nullptr && http_url[0] != '\0') {
    RunSuite<tc::InferenceServerHttpClient>("HTTP/ClientTest", http_url);
    ran = true;
  }
  if (grpc_url != nullptr && grpc_url[0] != '\0') {
    RunSuite<tc::InferenceServerGrpcClient>("GRPC/ClientTest", grpc_url);
    ran = true;
  }
  if (!ran) {
    std::printf("skip: set CLIENT_TPU_TEST_URL / CLIENT_TPU_TEST_GRPC_URL\n");
  }
  return g_failures == 0 ? 0 : 1;
}
