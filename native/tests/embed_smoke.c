/* C host for the embedded server (server_embed.h smoke).
 *
 * Proves the java-api-bindings parity story end-to-end from plain C: init
 * the interpreter, create a server with the "simple" model, run a
 * two-part-body inference, check the sum/diff arithmetic, hit the admin
 * JSON surfaces, start the HTTP frontend, destroy.
 *
 * Usage: embed_smoke <repo_path>
 * Exits 0 and prints PASS on success.
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "client_tpu/server_embed.h"

static int fail(const char* stage, char* error) {
  fprintf(stderr, "FAIL at %s: %s\n", stage,
          error != NULL ? error : "(no message)");
  ctpu_embed_free(error);
  return 1;
}

int main(int argc, char** argv) {
  const char* repo = argc > 1 ? argv[1] : NULL;
  char* error = NULL;

  if (ctpu_embed_init(repo, &error) != 0) return fail("init", error);

  int64_t server = ctpu_embed_server_create("{\"models\": [\"simple\"]}",
                                            &error);
  if (server == 0) return fail("create", error);

  /* two-part v2 body: JSON header + two INT32[1,16] binary tails */
  int32_t input0[16], input1[16];
  for (int i = 0; i < 16; i++) {
    input0[i] = i;
    input1[i] = 2 * i;
  }
  const char* header_json =
      "{\"inputs\":["
      "{\"name\":\"INPUT0\",\"datatype\":\"INT32\",\"shape\":[1,16],"
      "\"parameters\":{\"binary_data_size\":64}},"
      "{\"name\":\"INPUT1\",\"datatype\":\"INT32\",\"shape\":[1,16],"
      "\"parameters\":{\"binary_data_size\":64}}],"
      "\"outputs\":["
      "{\"name\":\"OUTPUT0\",\"parameters\":{\"binary_data\":true}},"
      "{\"name\":\"OUTPUT1\",\"parameters\":{\"binary_data\":true}}]}";
  size_t header_len = strlen(header_json);
  size_t body_len = header_len + sizeof(input0) + sizeof(input1);
  uint8_t* body = malloc(body_len);
  memcpy(body, header_json, header_len);
  memcpy(body + header_len, input0, sizeof(input0));
  memcpy(body + header_len + sizeof(input0), input1, sizeof(input1));

  uint8_t* response = NULL;
  size_t response_len = 0;
  int64_t response_header_len = -1;
  int rc = ctpu_embed_infer(server, "simple", "", body, body_len,
                            (int64_t)header_len, &response, &response_len,
                            &response_header_len, &error);
  free(body);
  if (rc != 0) return fail("infer", error);
  if (response_header_len <= 0 ||
      (size_t)response_header_len + 128 != response_len) {
    fprintf(stderr, "FAIL: unexpected response framing (header %lld of %zu)\n",
            (long long)response_header_len, response_len);
    return 1;
  }
  /* binary tail: OUTPUT0 (sum) then OUTPUT1 (diff), 64 bytes each */
  const int32_t* sum = (const int32_t*)(response + response_header_len);
  const int32_t* diff = sum + 16;
  for (int i = 0; i < 16; i++) {
    if (sum[i] != input0[i] + input1[i] || diff[i] != input0[i] - input1[i]) {
      fprintf(stderr, "FAIL: wrong arithmetic at %d: sum=%d diff=%d\n", i,
              sum[i], diff[i]);
      return 1;
    }
  }
  ctpu_embed_free(response);
  printf("ok embedded infer (sum/diff verified)\n");

  char* json = NULL;
  if (ctpu_embed_metadata(server, NULL, &json, &error) != 0)
    return fail("server metadata", error);
  if (strstr(json, "\"name\"") == NULL) {
    fprintf(stderr, "FAIL: metadata missing name: %s\n", json);
    return 1;
  }
  ctpu_embed_free(json);

  if (ctpu_embed_metadata(server, "simple", &json, &error) != 0)
    return fail("model metadata", error);
  if (strstr(json, "INPUT0") == NULL) {
    fprintf(stderr, "FAIL: model metadata missing INPUT0: %s\n", json);
    return 1;
  }
  ctpu_embed_free(json);

  if (ctpu_embed_repository_index(server, &json, &error) != 0)
    return fail("repository index", error);
  ctpu_embed_free(json);

  if (ctpu_embed_statistics(server, "", &json, &error) != 0)
    return fail("statistics", error);
  if (strstr(json, "simple") == NULL) {
    fprintf(stderr, "FAIL: statistics missing model row: %s\n", json);
    return 1;
  }
  ctpu_embed_free(json);
  printf("ok admin surfaces\n");

  int port = 0;
  if (ctpu_embed_start_http(server, &port, &error) != 0)
    return fail("start_http", error);
  if (port <= 0) {
    fprintf(stderr, "FAIL: http port %d\n", port);
    return 1;
  }
  printf("ok http frontend on port %d\n", port);

  /* error path: unknown model must fail cleanly, not crash */
  rc = ctpu_embed_infer(server, "no_such_model", "", (const uint8_t*)"{}", 2,
                        -1, &response, &response_len, &response_header_len,
                        &error);
  if (rc == 0) {
    fprintf(stderr, "FAIL: unknown model inference succeeded\n");
    return 1;
  }
  ctpu_embed_free(error);
  error = NULL;
  printf("ok typed error on unknown model\n");

  if (ctpu_embed_server_destroy(server, &error) != 0)
    return fail("destroy", error);

  printf("PASS embed_smoke\n");
  return 0;
}
