// HPACK decoder cross-validation tool.
// stdin: lines of hex-encoded HPACK header blocks (one connection's ordered
// sequence — the dynamic table persists across lines, as across HEADERS
// frames). stdout: per block, "name\tvalue" lines then a blank line; on
// decode error, "ERROR <msg>". Driven by tests/test_native.py against the
// reference `hpack` PyPI encoder output.
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "client_tpu/h2.h"

using client_tpu::Error;
using client_tpu::h2::HeaderList;
using client_tpu::h2::HpackDecoder;

static bool HexDecode(const std::string& hex, std::string* out) {
  if (hex.size() % 2 != 0) return false;
  out->clear();
  for (size_t i = 0; i < hex.size(); i += 2) {
    char buf[3] = {hex[i], hex[i + 1], 0};
    char* end = nullptr;
    long v = strtol(buf, &end, 16);
    if (end != buf + 2) return false;
    out->push_back(static_cast<char>(v));
  }
  return true;
}

int main() {
  HpackDecoder decoder;
  std::string hex;
  while (std::getline(std::cin, hex)) {
    while (!hex.empty() && (hex.back() == '\n' || hex.back() == '\r')) {
      hex.pop_back();
    }
    if (hex.empty()) continue;
    std::string block;
    if (!HexDecode(hex, &block)) {
      printf("ERROR bad hex input\n\n");
      continue;
    }
    HeaderList headers;
    Error err = decoder.Decode(
        reinterpret_cast<const uint8_t*>(block.data()), block.size(), &headers);
    if (err) {
      printf("ERROR %s\n\n", err.Message().c_str());
      continue;
    }
    for (const auto& kv : headers) {
      printf("%s\t%s\n", kv.first.c_str(), kv.second.c_str());
    }
    printf("\n");
    fflush(stdout);
  }
  return 0;
}
