// GRPC client implementation: unary gRPC framed by hand over libcurl HTTP/2.
// See grpc_client.h for the design rationale vs the reference's grpc++ stub
// client (src/c++/library/grpc_client.cc). Field numbers follow the public
// KServe protocol (reference src/rust/triton-client/proto/grpc_service.proto)
// and mirror the Python specs in client_tpu/grpc/_messages.py.

#include "client_tpu/grpc_client.h"

#include <zlib.h>

#include <cstring>

#include "client_tpu/pbwire.h"

namespace client_tpu {

namespace {

// -- gRPC message compression (grpc-encoding: gzip | deflate) ---------------
// Reference parity: grpc channel compression (Python grpc/_client.py
// compression_algorithm; C++ grpc_client.cc channel args). "gzip" is the
// RFC 1952 format, "deflate" the RFC 1950 zlib stream; decompression
// auto-detects either via windowBits 15+32.

Error ZCompress(const std::string& in, std::string* out, bool gzip_format) {
  z_stream zs = {};
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED,
                   15 + (gzip_format ? 16 : 0), 8,
                   Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("zlib deflateInit failed");
  }
  out->resize(deflateBound(&zs, in.size()));
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  zs.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  zs.avail_out = static_cast<uInt>(out->size());
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) return Error("zlib deflate failed");
  out->resize(zs.total_out);
  return Error::Success();
}

// Decompression-bomb guard: the reference clients bound inbound messages
// via max_receive_message_length (2^31-1 default); match that ceiling so a
// hostile peer cannot amplify a small frame into unbounded allocation.
constexpr size_t kMaxDecompressedSize = (1ull << 31) - 1;

Error ZDecompress(const std::string& in, std::string* out) {
  z_stream zs = {};
  if (inflateInit2(&zs, 15 + 32) != Z_OK) {  // auto-detect gzip/zlib
    return Error("zlib inflateInit failed");
  }
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  out->clear();
  char buf[64 * 1024];
  int rc = Z_OK;
  while (rc == Z_OK) {
    zs.next_out = reinterpret_cast<Bytef*>(buf);
    zs.avail_out = sizeof(buf);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return Error("corrupt compressed gRPC message");
    }
    out->append(buf, sizeof(buf) - zs.avail_out);
    if (out->size() > kMaxDecompressedSize) {
      inflateEnd(&zs);
      return Error("compressed gRPC message decompresses beyond the 2GiB receive limit");
    }
  }
  inflateEnd(&zs);
  return Error::Success();
}

// Frame `payload`, compressing per `algorithm` ("gzip", "deflate",
// "identity", or ""). Incompressible payloads fall back to flag-0
// uncompressed framing (legal with grpc-encoding set, and what grpc-core
// does) so enabling compression never enlarges the wire bytes.
Error FrameMaybeCompressed(
    const std::string& payload, const std::string& algorithm,
    std::string* out) {
  if (algorithm.empty() || algorithm == "identity") {
    pb::FrameMessage(payload, out);
    return Error::Success();
  }
  if (algorithm != "gzip" && algorithm != "deflate") {
    return Error("unsupported compression_algorithm '" + algorithm +
                 "' (supported: gzip, deflate, identity)");
  }
  std::string packed;
  Error err = ZCompress(payload, &packed, algorithm == "gzip");
  if (err) return err;
  if (packed.size() >= payload.size()) {
    pb::FrameMessage(payload, out);
  } else {
    pb::FrameMessage(packed, out, /*compressed=*/true);
  }
  return Error::Success();
}

const char* kStatusNames[] = {
    "OK", "CANCELLED", "UNKNOWN", "INVALID_ARGUMENT", "DEADLINE_EXCEEDED",
    "NOT_FOUND", "ALREADY_EXISTS", "PERMISSION_DENIED", "RESOURCE_EXHAUSTED",
    "FAILED_PRECONDITION", "ABORTED", "OUT_OF_RANGE", "UNIMPLEMENTED",
    "INTERNAL", "UNAVAILABLE", "DATA_LOSS", "UNAUTHENTICATED"};

std::string GrpcStatusName(long code) {
  if (code >= 0 && code < static_cast<long>(sizeof(kStatusNames) / sizeof(char*))) {
    return kStatusNames[code];
  }
  return "CODE_" + std::to_string(code);
}

std::string PercentDecode(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == '%' && i + 2 < in.size()) {
      char hex[3] = {in[i + 1], in[i + 2], 0};
      char* end = nullptr;
      long v = strtol(hex, &end, 16);
      if (end == hex + 2) {
        out.push_back(static_cast<char>(v));
        i += 2;
        continue;
      }
    }
    out.push_back(in[i]);
  }
  return out;
}

// The h2 layer merges response headers and trailers into one lowercased
// map; grpc-status normally rides the trailers (or headers on a
// trailers-only error response).
Error GrpcStatusToError(const std::map<std::string, std::string>& headers) {
  auto it = headers.find("grpc-status");
  if (it == headers.end()) {
    return Error("no grpc-status in response (not a gRPC endpoint?)");
  }
  long code = strtol(it->second.c_str(), nullptr, 10);
  if (code == 0) return Error::Success();
  std::string message;
  auto mit = headers.find("grpc-message");
  if (mit != headers.end()) message = PercentDecode(mit->second);
  return Error("[StatusCode." + GrpcStatusName(code) + "] " + message);
}

// -- InferParameter (oneof bool=1 int64=2 string=3 double=4 uint64=5) -------

void EncodeParamBool(std::string* out, bool v) {
  pb::Writer w(out);
  w.Tag(1, 0);
  w.Varint(v ? 1 : 0);
}
void EncodeParamInt64(std::string* out, int64_t v) {
  pb::Writer w(out);
  w.Tag(2, 0);
  w.Varint(static_cast<uint64_t>(v));
}
void EncodeParamString(std::string* out, const std::string& v) {
  pb::Writer w(out);
  w.Tag(3, 2);
  w.Varint(v.size());
  out->append(v);
}

Json DecodeInferParameter(const uint8_t* data, size_t size) {
  pb::Reader r(data, size);
  uint32_t field, wt;
  Json out;
  while (r.Next(&field, &wt)) {
    switch (field) {
      case 1:
        out = Json(r.BoolVal());
        break;
      case 2:
        out = Json(static_cast<int64_t>(r.SignedVarint()));
        break;
      case 3:
        out = Json(r.StringVal());
        break;
      case 4: {
        r.Skip(wt);  // double_param: rare; skipped (kept as null)
        break;
      }
      case 5:
        out = Json(static_cast<int64_t>(r.Varint()));
        break;
      default:
        r.Skip(wt);
    }
  }
  return out;
}

// map<string, InferParameter> entry
void EncodeStringParamEntry(
    pb::Writer* w, uint32_t field, const std::string& key,
    const std::string& param_payload) {
  std::string entry;
  pb::Writer e(&entry);
  e.String(1, key);
  e.Submessage(2, param_payload);
  w->Submessage(field, entry);
}

void AppendShmParams(
    std::string* tensor, uint32_t params_field, const std::string& region,
    size_t byte_size, size_t offset) {
  pb::Writer w(tensor);
  std::string p;
  EncodeParamString(&p, region);
  EncodeStringParamEntry(&w, params_field, "shared_memory_region", p);
  p.clear();
  EncodeParamInt64(&p, static_cast<int64_t>(byte_size));
  EncodeStringParamEntry(&w, params_field, "shared_memory_byte_size", p);
  if (offset != 0) {
    p.clear();
    EncodeParamInt64(&p, static_cast<int64_t>(offset));
    EncodeStringParamEntry(&w, params_field, "shared_memory_offset", p);
  }
}

// -- ModelInferRequest ------------------------------------------------------

std::string EncodeInferRequest(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  std::string out;
  pb::Writer w(&out);
  w.String(1, options.model_name);
  w.String(2, options.model_version);
  w.String(3, options.request_id);

  // parameters (field 4)
  std::string p;
  if (!options.sequence_id_str.empty()) {
    p.clear();
    EncodeParamString(&p, options.sequence_id_str);
    EncodeStringParamEntry(&w, 4, "sequence_id", p);
  } else if (options.sequence_id != 0) {
    p.clear();
    EncodeParamInt64(&p, static_cast<int64_t>(options.sequence_id));
    EncodeStringParamEntry(&w, 4, "sequence_id", p);
  }
  if (options.sequence_id != 0 || !options.sequence_id_str.empty()) {
    p.clear();
    EncodeParamBool(&p, options.sequence_start);
    EncodeStringParamEntry(&w, 4, "sequence_start", p);
    p.clear();
    EncodeParamBool(&p, options.sequence_end);
    EncodeStringParamEntry(&w, 4, "sequence_end", p);
  }
  if (options.priority != 0) {
    p.clear();
    EncodeParamInt64(&p, static_cast<int64_t>(options.priority));
    EncodeStringParamEntry(&w, 4, "priority", p);
  }
  if (options.server_timeout_us != 0) {
    p.clear();
    EncodeParamInt64(&p, static_cast<int64_t>(options.server_timeout_us));
    EncodeStringParamEntry(&w, 4, "timeout", p);
  }
  if (options.enable_empty_final_response) {
    p.clear();
    EncodeParamBool(&p, true);
    EncodeStringParamEntry(&w, 4, "triton_enable_empty_final_response", p);
  }
  for (const auto& kv : options.request_parameters) {
    p.clear();
    EncodeParamString(&p, kv.second);
    EncodeStringParamEntry(&w, 4, kv.first, p);
  }

  // inputs (field 5) + raw chunks gathered for field 7
  for (const auto* input : inputs) {
    std::string tensor;
    pb::Writer t(&tensor);
    t.String(1, input->Name());
    t.String(2, input->Datatype());
    t.PackedInt64(3, input->Shape());
    if (input->InSharedMemory()) {
      AppendShmParams(
          &tensor, 4, input->SharedMemoryRegion(),
          input->SharedMemoryByteSize(), input->SharedMemoryOffset());
    }
    w.Submessage(5, tensor);
  }

  // requested outputs (field 6)
  for (const auto* output : outputs) {
    std::string tensor;
    pb::Writer t(&tensor);
    t.String(1, output->Name());
    if (output->ClassCount() > 0) {
      std::string cp;
      EncodeParamInt64(&cp, static_cast<int64_t>(output->ClassCount()));
      EncodeStringParamEntry(&t, 2, "classification", cp);
    }
    if (output->InSharedMemory()) {
      AppendShmParams(
          &tensor, 2, output->SharedMemoryRegion(),
          output->SharedMemoryByteSize(), output->SharedMemoryOffset());
    }
    w.Submessage(6, tensor);
  }

  // raw_input_contents (field 7): one bytes element per non-shm input,
  // scatter-gather chunks concatenated directly into the body
  for (const auto* input : inputs) {
    if (input->InSharedMemory()) continue;
    w.Tag(7, 2);
    w.Varint(input->ByteSize());
    for (const auto& buf : input->Buffers()) {
      out.append(reinterpret_cast<const char*>(buf.first), buf.second);
    }
  }
  return out;
}

// -- ModelInferResponse -> InferResult --------------------------------------

class InferResultGrpc : public InferResult {
 public:
  // Takes ownership of the serialized response payload; output raw views
  // point into it.
  static Error Create(
      InferResult** result, std::string&& payload, Error request_status) {
    auto* r = new InferResultGrpc(std::move(payload));
    if (request_status) {
      r->status_ = request_status;
      *result = r;
      return Error::Success();
    }
    Error err = r->Parse();
    if (err) {
      delete r;
      return err;
    }
    *result = r;
    return Error::Success();
  }

  Error ModelName(std::string* name) const override {
    if (status_) return status_;
    *name = model_name_;
    return Error::Success();
  }
  Error ModelVersion(std::string* version) const override {
    if (status_) return status_;
    *version = model_version_;
    return Error::Success();
  }
  Error Id(std::string* id) const override {
    if (status_) return status_;
    *id = id_;
    return Error::Success();
  }
  Error OutputNames(std::vector<std::string>* names) const override {
    if (status_) return status_;
    names->clear();
    for (const auto& o : outputs_) names->push_back(o.name);
    return Error::Success();
  }
  Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const override {
    const Output* o = Find(output_name);
    if (o == nullptr) return Error("unknown output '" + output_name + "'");
    *shape = o->shape;
    return Error::Success();
  }
  Error Datatype(
      const std::string& output_name, std::string* datatype) const override {
    const Output* o = Find(output_name);
    if (o == nullptr) return Error("unknown output '" + output_name + "'");
    *datatype = o->datatype;
    return Error::Success();
  }
  Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const override {
    const Output* o = Find(output_name);
    if (o == nullptr) return Error("unknown output '" + output_name + "'");
    *buf = o->data;
    *byte_size = o->size;
    return Error::Success();
  }
  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override {
    const Output* o = Find(output_name);
    if (o == nullptr) return Error("unknown output '" + output_name + "'");
    if (!o->bytes_elements.empty()) {
      *string_result = o->bytes_elements;
      return Error::Success();
    }
    return DeserializeStrings(o->data, o->size, string_result);
  }
  Error IsFinalResponse(bool* is_final) const override {
    *is_final = is_final_;
    return Error::Success();
  }
  Error IsNullResponse(bool* is_null) const override {
    *is_null = outputs_.empty() && is_final_;
    return Error::Success();
  }
  std::string DebugString() const override {
    if (status_) return status_.Message();
    std::string out = "model=" + model_name_ + " outputs=[";
    for (const auto& o : outputs_) out += o.name + ",";
    out += "]";
    return out;
  }
  Error RequestStatus() const override { return status_; }

 private:
  explicit InferResultGrpc(std::string&& payload)
      : payload_(std::move(payload)) {}

  struct Output {
    std::string name;
    std::string datatype;
    std::vector<int64_t> shape;
    const uint8_t* data = nullptr;
    size_t size = 0;
    std::vector<std::string> bytes_elements;  // typed contents fallback
    std::string owned;  // 4-byte-length serialization of bytes_elements
    bool in_shm = false;
  };

  const Output* Find(const std::string& name) const {
    if (status_) return nullptr;
    for (const auto& o : outputs_) {
      if (o.name == name) return &o;
    }
    return nullptr;
  }

  Error Parse() {
    pb::Reader r(payload_.data(), payload_.size());
    uint32_t field, wt;
    std::vector<std::pair<const uint8_t*, size_t>> raws;
    while (r.Next(&field, &wt)) {
      switch (field) {
        case 1:
          model_name_ = r.StringVal();
          break;
        case 2:
          model_version_ = r.StringVal();
          break;
        case 3:
          id_ = r.StringVal();
          break;
        case 4: {  // parameters: look for triton_final_response
          const uint8_t* d;
          size_t n;
          if (!r.LengthDelimited(&d, &n)) break;
          pb::Reader entry(d, n);
          uint32_t ef, ewt;
          std::string key;
          Json value;
          while (entry.Next(&ef, &ewt)) {
            if (ef == 1) {
              key = entry.StringVal();
            } else if (ef == 2) {
              const uint8_t* pd;
              size_t pn;
              if (entry.LengthDelimited(&pd, &pn)) {
                value = DecodeInferParameter(pd, pn);
              }
            } else {
              entry.Skip(ewt);
            }
          }
          if (key == "triton_final_response") is_final_ = value.AsBool();
          break;
        }
        case 5: {  // outputs
          const uint8_t* d;
          size_t n;
          if (!r.LengthDelimited(&d, &n)) break;
          Output o;
          pb::Reader t(d, n);
          uint32_t tf, twt;
          while (t.Next(&tf, &twt)) {
            switch (tf) {
              case 1:
                o.name = t.StringVal();
                break;
              case 2:
                o.datatype = t.StringVal();
                break;
              case 3:
                t.RepeatedInt64(twt, &o.shape);
                break;
              case 4: {  // parameters: shm placement marker
                const uint8_t* pd;
                size_t pn;
                if (!t.LengthDelimited(&pd, &pn)) break;
                pb::Reader entry(pd, pn);
                uint32_t ef, ewt;
                while (entry.Next(&ef, &ewt)) {
                  if (ef == 1) {
                    if (entry.StringVal() == "shared_memory_region") {
                      o.in_shm = true;
                    }
                  } else {
                    entry.Skip(ewt);
                  }
                }
                break;
              }
              case 5: {  // typed contents: keep BYTES elements
                const uint8_t* cd;
                size_t cn;
                if (!t.LengthDelimited(&cd, &cn)) break;
                pb::Reader c(cd, cn);
                uint32_t cf, cwt;
                while (c.Next(&cf, &cwt)) {
                  if (cf == 8) {  // bytes_contents
                    o.bytes_elements.push_back(c.StringVal());
                  } else {
                    c.Skip(cwt);
                  }
                }
                break;
              }
              default:
                t.Skip(twt);
            }
          }
          outputs_.push_back(std::move(o));
          break;
        }
        case 6: {  // raw_output_contents, index-matched to outputs
          const uint8_t* d;
          size_t n;
          if (!r.LengthDelimited(&d, &n)) break;
          raws.emplace_back(d, n);
          break;
        }
        default:
          r.Skip(wt);
      }
    }
    if (!r.ok()) return Error("malformed ModelInferResponse");
    size_t raw_index = 0;
    for (auto& o : outputs_) {
      if (o.in_shm) continue;
      if (!o.bytes_elements.empty()) {
        // BYTES delivered via typed bytes_contents: materialize the
        // 4-byte-length raw form so RawData() consumers (the flat C API
        // and its ctypes binding deserialize through RawData only) see
        // the same bytes a raw_output_contents response would carry.
        o.owned.clear();
        for (const auto& elem : o.bytes_elements) {
          uint32_t len = static_cast<uint32_t>(elem.size());
          o.owned.append(reinterpret_cast<const char*>(&len), 4);
          o.owned.append(elem);
        }
        continue;
      }
      if (raw_index < raws.size()) {
        o.data = raws[raw_index].first;
        o.size = raws[raw_index].second;
        ++raw_index;
      }
    }
    // second pass: point data at the owned buffers only after outputs_ can
    // no longer reallocate (push_back above would dangle the pointers)
    for (auto& o : outputs_) {
      if (!o.bytes_elements.empty()) {
        o.data = reinterpret_cast<const uint8_t*>(o.owned.data());
        o.size = o.owned.size();
      }
    }
    return Error::Success();
  }

  std::string payload_;
  Error status_;
  std::string model_name_, model_version_, id_;
  std::vector<Output> outputs_;
  bool is_final_ = true;
};

// -- admin response decoders (proto -> Json) --------------------------------

Json DecodeTensorMetadataList(const uint8_t* d, size_t n) {
  Json tensor = Json::Object();
  pb::Reader t(d, n);
  uint32_t tf, twt;
  Json shape = Json::Array();
  while (t.Next(&tf, &twt)) {
    switch (tf) {
      case 1:
        tensor.Set("name", Json(t.StringVal()));
        break;
      case 2:
        tensor.Set("datatype", Json(t.StringVal()));
        break;
      case 3: {
        std::vector<int64_t> dims;
        t.RepeatedInt64(twt, &dims);
        for (int64_t v : dims) shape.Append(Json(v));
        break;
      }
      default:
        t.Skip(twt);
    }
  }
  tensor.Set("shape", std::move(shape));
  return tensor;
}

Json DecodeModelMetadata(const std::string& payload) {
  Json out = Json::Object();
  Json versions = Json::Array();
  Json inputs = Json::Array();
  Json outputs = Json::Array();
  pb::Reader r(payload.data(), payload.size());
  uint32_t field, wt;
  while (r.Next(&field, &wt)) {
    const uint8_t* d;
    size_t n;
    switch (field) {
      case 1:
        out.Set("name", Json(r.StringVal()));
        break;
      case 2:
        versions.Append(Json(r.StringVal()));
        break;
      case 3:
        out.Set("platform", Json(r.StringVal()));
        break;
      case 4:
        if (r.LengthDelimited(&d, &n)) {
          inputs.Append(DecodeTensorMetadataList(d, n));
        }
        break;
      case 5:
        if (r.LengthDelimited(&d, &n)) {
          outputs.Append(DecodeTensorMetadataList(d, n));
        }
        break;
      default:
        r.Skip(wt);
    }
  }
  out.Set("versions", std::move(versions));
  out.Set("inputs", std::move(inputs));
  out.Set("outputs", std::move(outputs));
  return out;
}

Json DecodeModelConfig(const uint8_t* data, size_t size) {
  Json cfg = Json::Object();
  Json inputs = Json::Array();
  Json outputs = Json::Array();
  pb::Reader r(data, size);
  uint32_t field, wt;
  auto decode_io = [](const uint8_t* d, size_t n) {
    Json io = Json::Object();
    Json dims = Json::Array();
    pb::Reader t(d, n);
    uint32_t tf, twt;
    while (t.Next(&tf, &twt)) {
      switch (tf) {
        case 1:
          io.Set("name", Json(t.StringVal()));
          break;
        case 2:
          io.Set("data_type", Json(static_cast<int64_t>(t.Varint())));
          break;
        case 4:
        case 3: {
          // ModelInput dims=4; ModelOutput dims=3 (3 is also ModelInput
          // "format" enum, which is varint — disambiguate by wire type)
          if (twt == 0) {
            io.Set("format", Json(static_cast<int64_t>(t.Varint())));
          } else {
            std::vector<int64_t> dv;
            t.RepeatedInt64(twt, &dv);
            for (int64_t v : dv) dims.Append(Json(v));
          }
          break;
        }
        default:
          t.Skip(twt);
      }
    }
    io.Set("dims", std::move(dims));
    return io;
  };
  while (r.Next(&field, &wt)) {
    const uint8_t* d;
    size_t n;
    switch (field) {
      case 1:
        cfg.Set("name", Json(r.StringVal()));
        break;
      case 2:
        cfg.Set("platform", Json(r.StringVal()));
        break;
      case 4:
        cfg.Set("max_batch_size", Json(static_cast<int64_t>(r.SignedVarint())));
        break;
      case 5:
        if (r.LengthDelimited(&d, &n)) inputs.Append(decode_io(d, n));
        break;
      case 6:
        if (r.LengthDelimited(&d, &n)) outputs.Append(decode_io(d, n));
        break;
      case 17:
        cfg.Set("backend", Json(r.StringVal()));
        break;
      case 25:
        cfg.Set("runtime", Json(r.StringVal()));
        break;
      default:
        r.Skip(wt);
    }
  }
  cfg.Set("input", std::move(inputs));
  cfg.Set("output", std::move(outputs));
  return cfg;
}

Json DecodeStatisticDuration(const uint8_t* d, size_t n) {
  Json out = Json::Object();
  pb::Reader r(d, n);
  uint32_t f, wt;
  while (r.Next(&f, &wt)) {
    if (f == 1) {
      out.Set("count", Json(static_cast<int64_t>(r.Varint())));
    } else if (f == 2) {
      out.Set("ns", Json(static_cast<int64_t>(r.Varint())));
    } else {
      r.Skip(wt);
    }
  }
  return out;
}

Json DecodeModelStatistics(const uint8_t* data, size_t size) {
  Json out = Json::Object();
  pb::Reader r(data, size);
  uint32_t field, wt;
  static const char* kDurations[] = {
      "",     "success",       "fail",          "queue",
      "compute_input", "compute_infer", "compute_output", "cache_hit",
      "cache_miss"};
  while (r.Next(&field, &wt)) {
    const uint8_t* d;
    size_t n;
    switch (field) {
      case 1:
        out.Set("name", Json(r.StringVal()));
        break;
      case 2:
        out.Set("version", Json(r.StringVal()));
        break;
      case 3:
        out.Set("last_inference", Json(static_cast<int64_t>(r.Varint())));
        break;
      case 4:
        out.Set("inference_count", Json(static_cast<int64_t>(r.Varint())));
        break;
      case 5:
        out.Set("execution_count", Json(static_cast<int64_t>(r.Varint())));
        break;
      case 6: {  // inference_stats
        if (!r.LengthDelimited(&d, &n)) break;
        Json stats = Json::Object();
        pb::Reader s(d, n);
        uint32_t sf, swt;
        while (s.Next(&sf, &swt)) {
          const uint8_t* sd;
          size_t sn;
          if (sf >= 1 && sf <= 8 && s.LengthDelimited(&sd, &sn)) {
            stats.Set(kDurations[sf], DecodeStatisticDuration(sd, sn));
          } else {
            s.Skip(swt);
          }
        }
        out.Set("inference_stats", std::move(stats));
        break;
      }
      default:
        r.Skip(wt);
    }
  }
  return out;
}

// map<string, RegionStatus> for the three shm families
Json DecodeShmStatus(const std::string& payload, bool device_family) {
  Json regions = Json::Object();
  pb::Reader r(payload.data(), payload.size());
  uint32_t field, wt;
  while (r.Next(&field, &wt)) {
    if (field != 1) {
      r.Skip(wt);
      continue;
    }
    const uint8_t* d;
    size_t n;
    if (!r.LengthDelimited(&d, &n)) break;
    pb::Reader entry(d, n);
    uint32_t ef, ewt;
    std::string key;
    Json status = Json::Object();
    while (entry.Next(&ef, &ewt)) {
      if (ef == 1) {
        key = entry.StringVal();
      } else if (ef == 2) {
        const uint8_t* sd;
        size_t sn;
        if (!entry.LengthDelimited(&sd, &sn)) break;
        pb::Reader s(sd, sn);
        uint32_t sf, swt;
        while (s.Next(&sf, &swt)) {
          if (device_family) {
            // RegionStatus: name=1 device_id=2 byte_size=3
            if (sf == 1) {
              status.Set("name", Json(s.StringVal()));
            } else if (sf == 2) {
              status.Set("device_id", Json(static_cast<int64_t>(s.Varint())));
            } else if (sf == 3) {
              status.Set("byte_size", Json(static_cast<int64_t>(s.Varint())));
            } else {
              s.Skip(swt);
            }
          } else {
            // RegionStatus: name=1 key=2 offset=3 byte_size=4
            if (sf == 1) {
              status.Set("name", Json(s.StringVal()));
            } else if (sf == 2) {
              status.Set("key", Json(s.StringVal()));
            } else if (sf == 3) {
              status.Set("offset", Json(static_cast<int64_t>(s.Varint())));
            } else if (sf == 4) {
              status.Set("byte_size", Json(static_cast<int64_t>(s.Varint())));
            } else {
              s.Skip(swt);
            }
          }
        }
      } else {
        entry.Skip(ewt);
      }
    }
    regions.Set(key, std::move(status));
  }
  return regions;
}

// TraceSetting/LogSettings settings maps
Json DecodeTraceSettings(const std::string& payload) {
  Json settings = Json::Object();
  pb::Reader r(payload.data(), payload.size());
  uint32_t field, wt;
  while (r.Next(&field, &wt)) {
    if (field != 1) {
      r.Skip(wt);
      continue;
    }
    const uint8_t* d;
    size_t n;
    if (!r.LengthDelimited(&d, &n)) break;
    pb::Reader entry(d, n);
    uint32_t ef, ewt;
    std::string key;
    Json values = Json::Array();
    while (entry.Next(&ef, &ewt)) {
      if (ef == 1) {
        key = entry.StringVal();
      } else if (ef == 2) {
        const uint8_t* vd;
        size_t vn;
        if (!entry.LengthDelimited(&vd, &vn)) break;
        pb::Reader v(vd, vn);
        uint32_t vf, vwt;
        while (v.Next(&vf, &vwt)) {
          if (vf == 1) {
            values.Append(Json(v.StringVal()));
          } else {
            v.Skip(vwt);
          }
        }
      } else {
        entry.Skip(ewt);
      }
    }
    settings.Set(key, std::move(values));
  }
  return settings;
}

Json DecodeLogSettings(const std::string& payload) {
  Json settings = Json::Object();
  pb::Reader r(payload.data(), payload.size());
  uint32_t field, wt;
  while (r.Next(&field, &wt)) {
    if (field != 1) {
      r.Skip(wt);
      continue;
    }
    const uint8_t* d;
    size_t n;
    if (!r.LengthDelimited(&d, &n)) break;
    pb::Reader entry(d, n);
    uint32_t ef, ewt;
    std::string key;
    Json value;
    while (entry.Next(&ef, &ewt)) {
      if (ef == 1) {
        key = entry.StringVal();
      } else if (ef == 2) {
        const uint8_t* vd;
        size_t vn;
        if (!entry.LengthDelimited(&vd, &vn)) break;
        pb::Reader v(vd, vn);
        uint32_t vf, vwt;
        while (v.Next(&vf, &vwt)) {
          if (vf == 1) {
            value = Json(v.BoolVal());
          } else if (vf == 2) {
            value = Json(static_cast<int64_t>(v.Varint()));
          } else if (vf == 3) {
            value = Json(v.StringVal());
          } else {
            v.Skip(vwt);
          }
        }
      } else {
        entry.Skip(ewt);
      }
    }
    settings.Set(key, value);
  }
  return settings;
}

// settings Json -> TraceSettingRequest map entries (field 1; the caller
// writes model_name as field 2)
void EncodeTraceSettings(pb::Writer* w, const Json& settings) {
  for (const auto& kv : settings.items()) {
    std::string value;
    pb::Writer v(&value);
    if (kv.second.is_array()) {
      for (size_t i = 0; i < kv.second.size(); ++i) {
        v.String(1, kv.second[i].type() == Json::Type::kString
                        ? kv.second[i].AsString()
                        : kv.second[i].Dump());
      }
    } else if (!kv.second.is_null()) {
      v.String(1, kv.second.type() == Json::Type::kString
                      ? kv.second.AsString()
                      : kv.second.Dump());
    }  // null -> empty SettingValue (clears to global default)
    std::string entry;
    pb::Writer e(&entry);
    e.String(1, kv.first);
    e.Submessage(2, value);
    w->Submessage(1, entry);
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// client
// ---------------------------------------------------------------------------

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& server_url, bool verbose,
    const tls::TlsOptions& ssl_options) {
  client->reset(
      new InferenceServerGrpcClient(server_url, verbose, ssl_options));
  return Error::Success();
}

InferenceServerGrpcClient::InferenceServerGrpcClient(
    const std::string& url, bool verbose, const tls::TlsOptions& ssl)
    : url_(url), verbose_(verbose), ssl_options_(ssl) {}

InferenceServerGrpcClient::~InferenceServerGrpcClient() {
  StopStream();
  {
    // under the mutex: otherwise the notify can fire between the worker's
    // predicate check and its wait, and join() blocks forever
    std::lock_guard<std::mutex> lock(queue_mutex_);
    exiting_ = true;
  }
  queue_cv_.notify_all();
  if (worker_.joinable()) worker_.join();
}

struct InferenceServerGrpcClient::AsyncRequest {
  std::string method;
  std::string body;  // already framed
  Headers headers;
  uint64_t timeout_us = 0;
  OnComplete callback;
  RequestTimers timers;
};

InferenceServerGrpcClient::Headers InferenceServerGrpcClient::MergedHeaders(
    const Headers& headers) {
  std::lock_guard<std::mutex> lock(default_headers_mutex_);
  Headers merged = default_headers_;
  for (const auto& kv : headers) merged[kv.first] = kv.second;
  return merged;
}

std::unique_ptr<h2::Connection> InferenceServerGrpcClient::AcquireConnection(
    Error* err) {
  {
    std::lock_guard<std::mutex> lock(pool_mutex_);
    while (!idle_.empty()) {
      std::unique_ptr<h2::Connection> conn = std::move(idle_.back());
      idle_.pop_back();
      if (conn->Reusable()) return conn;
    }
  }
  std::unique_ptr<h2::Connection> conn;
  *err = h2::Connection::Connect(&conn, url_, 10000, &ssl_options_);
  if (*err) {
    *err = Error("[StatusCode.UNAVAILABLE] " + err->Message());
    return nullptr;
  }
  return conn;
}

void InferenceServerGrpcClient::ReleaseConnection(
    std::unique_ptr<h2::Connection> conn) {
  // a draining (GOAWAY) connection must not go back in the pool: its
  // socket can stay open long after new streams started being refused
  if (conn == nullptr || !conn->Reusable()) return;
  std::lock_guard<std::mutex> lock(pool_mutex_);
  idle_.push_back(std::move(conn));
}

namespace {
h2::HeaderList GrpcRequestHeaders(
    const InferenceServerGrpcClient::Headers& extra,
    const std::string& compression = "") {
  h2::HeaderList headers = {
      {"content-type", "application/grpc"},
      {"te", "trailers"},
      // always advertise: the server may compress responses either way
      {"grpc-accept-encoding", "identity, deflate, gzip"},
  };
  if (!compression.empty()) {
    headers.emplace_back("grpc-encoding", compression);
  }
  for (const auto& kv : extra) headers.emplace_back(kv.first, kv.second);
  return headers;
}

// Unframe + (if flagged) decompress one response message into *response.
// `allow_empty`: admin RPCs legitimately answer with a zero-length body;
// ModelInfer never does, so the async path keeps it a protocol error.
Error UnpackResponse(
    const std::string& body, std::string* response, bool allow_empty) {
  size_t pos = 0;
  const uint8_t* payload;
  size_t payload_size;
  bool compressed;
  if (!pb::UnframeMessage(body, &pos, &payload, &payload_size, &compressed)) {
    if (body.empty() && allow_empty) {
      response->clear();
      return Error::Success();
    }
    return Error("truncated gRPC response frame");
  }
  if (compressed) {
    return ZDecompress(
        std::string(reinterpret_cast<const char*>(payload), payload_size),
        response);
  }
  response->assign(reinterpret_cast<const char*>(payload), payload_size);
  return Error::Success();
}
}  // namespace

Error InferenceServerGrpcClient::Call(
    const std::string& method, const std::string& request,
    std::string* response, const Headers& headers, uint64_t timeout_us,
    const std::string& compression) {
  std::string body;
  Error frame_err = FrameMaybeCompressed(request, compression, &body);
  if (frame_err) return frame_err;
  Error err;
  std::unique_ptr<h2::Connection> conn = AcquireConnection(&err);
  if (err) return err;
  h2::Connection::Response resp;
  err = conn->Request(
      "/inference.GRPCInferenceService/" + method,
      GrpcRequestHeaders(MergedHeaders(headers), compression),
      body, &resp,
      // round sub-ms timeouts UP: truncating to 0 would mean "no timeout"
      timeout_us == 0 ? 0 : static_cast<int64_t>((timeout_us + 999) / 1000));
  if (err) {
    // transport failure: the connection is not reusable
    if (err.Message() == "Deadline Exceeded") {
      return Error("[StatusCode.DEADLINE_EXCEEDED] Deadline Exceeded");
    }
    return Error("[StatusCode.UNAVAILABLE] " + err.Message());
  }
  ReleaseConnection(std::move(conn));
  if (verbose_) {
    fprintf(stderr, "grpc %s -> :status %d, %zu body bytes\n", method.c_str(),
            resp.status, resp.body.size());
  }
  Error status = GrpcStatusToError(resp.headers);
  if (status) return status;
  return UnpackResponse(resp.body, response, /*allow_empty=*/true);
}

void InferenceServerGrpcClient::SetCompression(const std::string& algorithm) {
  std::lock_guard<std::mutex> lock(default_headers_mutex_);
  default_compression_ = algorithm;
}

std::string InferenceServerGrpcClient::DefaultCompression() {
  std::lock_guard<std::mutex> lock(default_headers_mutex_);
  return default_compression_;
}

// -- health / metadata ------------------------------------------------------

Error InferenceServerGrpcClient::IsServerLive(bool* live, const Headers& h) {
  std::string resp;
  Error err = Call("ServerLive", "", &resp, h);
  if (err) return err;
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  *live = false;
  while (r.Next(&f, &wt)) {
    if (f == 1) {
      *live = r.BoolVal();
    } else {
      r.Skip(wt);
    }
  }
  return Error::Success();
}

Error InferenceServerGrpcClient::IsServerReady(bool* ready, const Headers& h) {
  std::string resp;
  Error err = Call("ServerReady", "", &resp, h);
  if (err) return err;
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  *ready = false;
  while (r.Next(&f, &wt)) {
    if (f == 1) {
      *ready = r.BoolVal();
    } else {
      r.Skip(wt);
    }
  }
  return Error::Success();
}

Error InferenceServerGrpcClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, model_name);
  w.String(2, model_version);
  std::string resp;
  Error err = Call("ModelReady", req, &resp, h);
  if (err) return err;
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  *ready = false;
  while (r.Next(&f, &wt)) {
    if (f == 1) {
      *ready = r.BoolVal();
    } else {
      r.Skip(wt);
    }
  }
  return Error::Success();
}

Error InferenceServerGrpcClient::ServerMetadata(
    Json* metadata, const Headers& h) {
  std::string resp;
  Error err = Call("ServerMetadata", "", &resp, h);
  if (err) return err;
  Json out = Json::Object();
  Json extensions = Json::Array();
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  while (r.Next(&f, &wt)) {
    switch (f) {
      case 1:
        out.Set("name", Json(r.StringVal()));
        break;
      case 2:
        out.Set("version", Json(r.StringVal()));
        break;
      case 3:
        extensions.Append(Json(r.StringVal()));
        break;
      default:
        r.Skip(wt);
    }
  }
  out.Set("extensions", std::move(extensions));
  *metadata = std::move(out);
  return Error::Success();
}

Error InferenceServerGrpcClient::ModelMetadata(
    Json* metadata, const std::string& model_name,
    const std::string& model_version, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, model_name);
  w.String(2, model_version);
  std::string resp;
  Error err = Call("ModelMetadata", req, &resp, h);
  if (err) return err;
  *metadata = DecodeModelMetadata(resp);
  return Error::Success();
}

Error InferenceServerGrpcClient::ModelConfig(
    Json* config, const std::string& model_name,
    const std::string& model_version, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, model_name);
  w.String(2, model_version);
  std::string resp;
  Error err = Call("ModelConfig", req, &resp, h);
  if (err) return err;
  Json out = Json::Object();
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  while (r.Next(&f, &wt)) {
    if (f == 1) {
      const uint8_t* d;
      size_t n;
      if (r.LengthDelimited(&d, &n)) out.Set("config", DecodeModelConfig(d, n));
    } else {
      r.Skip(wt);
    }
  }
  *config = std::move(out);
  return Error::Success();
}

Error InferenceServerGrpcClient::ModelRepositoryIndex(
    Json* index, const Headers& h) {
  std::string resp;
  Error err = Call("RepositoryIndex", "", &resp, h);
  if (err) return err;
  Json models = Json::Array();
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  while (r.Next(&f, &wt)) {
    if (f != 1) {
      r.Skip(wt);
      continue;
    }
    const uint8_t* d;
    size_t n;
    if (!r.LengthDelimited(&d, &n)) break;
    Json model = Json::Object();
    pb::Reader m(d, n);
    uint32_t mf, mwt;
    while (m.Next(&mf, &mwt)) {
      switch (mf) {
        case 1:
          model.Set("name", Json(m.StringVal()));
          break;
        case 2:
          model.Set("version", Json(m.StringVal()));
          break;
        case 3:
          model.Set("state", Json(m.StringVal()));
          break;
        case 4:
          model.Set("reason", Json(m.StringVal()));
          break;
        default:
          m.Skip(mwt);
      }
    }
    models.Append(std::move(model));
  }
  *index = std::move(models);
  return Error::Success();
}

Error InferenceServerGrpcClient::LoadModel(
    const std::string& model_name, const std::string& config,
    const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(2, model_name);
  if (!config.empty()) {
    std::string param;
    pb::Writer p(&param);
    p.Tag(3, 2);  // string_param (oneof)
    p.Varint(config.size());
    param.append(config);
    std::string entry;
    pb::Writer e(&entry);
    e.String(1, "config");
    e.Submessage(2, param);
    w.Submessage(3, entry);
  }
  std::string resp;
  return Call("RepositoryModelLoad", req, &resp, h);
}

Error InferenceServerGrpcClient::UnloadModel(
    const std::string& model_name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(2, model_name);
  std::string resp;
  return Call("RepositoryModelUnload", req, &resp, h);
}

Error InferenceServerGrpcClient::ModelInferenceStatistics(
    Json* stats, const std::string& model_name,
    const std::string& model_version, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, model_name);
  w.String(2, model_version);
  std::string resp;
  Error err = Call("ModelStatistics", req, &resp, h);
  if (err) return err;
  Json model_stats = Json::Array();
  pb::Reader r(resp.data(), resp.size());
  uint32_t f, wt;
  while (r.Next(&f, &wt)) {
    if (f == 1) {
      const uint8_t* d;
      size_t n;
      if (r.LengthDelimited(&d, &n)) {
        model_stats.Append(DecodeModelStatistics(d, n));
      }
    } else {
      r.Skip(wt);
    }
  }
  Json out = Json::Object();
  out.Set("model_stats", std::move(model_stats));
  *stats = std::move(out);
  return Error::Success();
}

Error InferenceServerGrpcClient::UpdateTraceSettings(
    Json* response, const std::string& model_name, const Json& settings,
    const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  EncodeTraceSettings(&w, settings);
  w.String(2, model_name);
  std::string resp;
  Error err = Call("TraceSetting", req, &resp, h);
  if (err) return err;
  if (response != nullptr) *response = DecodeTraceSettings(resp);
  return Error::Success();
}

Error InferenceServerGrpcClient::GetTraceSettings(
    Json* settings, const std::string& model_name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(2, model_name);
  std::string resp;
  Error err = Call("TraceSetting", req, &resp, h);
  if (err) return err;
  *settings = DecodeTraceSettings(resp);
  return Error::Success();
}

Error InferenceServerGrpcClient::UpdateLogSettings(
    Json* response, const Json& settings, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  for (const auto& kv : settings.items()) {
    std::string value;
    pb::Writer v(&value);
    switch (kv.second.type()) {
      case Json::Type::kBool:
        v.Tag(1, 0);
        v.Varint(kv.second.AsBool() ? 1 : 0);
        break;
      case Json::Type::kInt:
      case Json::Type::kDouble:
        v.Tag(2, 0);
        v.Varint(static_cast<uint64_t>(kv.second.AsInt()));
        break;
      default:
        v.Tag(3, 2);
        v.Varint(kv.second.AsString().size());
        value.append(kv.second.AsString());
    }
    std::string entry;
    pb::Writer e(&entry);
    e.String(1, kv.first);
    e.Submessage(2, value);
    w.Submessage(1, entry);
  }
  std::string resp;
  Error err = Call("LogSettings", req, &resp, h);
  if (err) return err;
  if (response != nullptr) *response = DecodeLogSettings(resp);
  return Error::Success();
}

Error InferenceServerGrpcClient::GetLogSettings(
    Json* settings, const Headers& h) {
  std::string resp;
  Error err = Call("LogSettings", "", &resp, h);
  if (err) return err;
  *settings = DecodeLogSettings(resp);
  return Error::Success();
}

// -- shared memory ----------------------------------------------------------

Error InferenceServerGrpcClient::SystemSharedMemoryStatus(
    Json* status, const std::string& name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  std::string resp;
  Error err = Call("SystemSharedMemoryStatus", req, &resp, h);
  if (err) return err;
  *status = DecodeShmStatus(resp, /*device_family=*/false);
  return Error::Success();
}

Error InferenceServerGrpcClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  w.String(2, key);
  w.Uint64(3, offset);
  w.Uint64(4, byte_size);
  std::string resp;
  return Call("SystemSharedMemoryRegister", req, &resp, h);
}

Error InferenceServerGrpcClient::UnregisterSystemSharedMemory(
    const std::string& name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  std::string resp;
  return Call("SystemSharedMemoryUnregister", req, &resp, h);
}

namespace {
void EncodeDeviceShmRegister(
    const std::string& name, const std::string& raw_handle, int device_id,
    size_t byte_size, std::string* req) {
  pb::Writer w(req);
  w.String(1, name);
  w.Bytes(2, raw_handle.data(), raw_handle.size());
  w.Int64(3, device_id);
  w.Uint64(4, byte_size);
}
}  // namespace

Error InferenceServerGrpcClient::TpuSharedMemoryStatus(
    Json* status, const std::string& name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  std::string resp;
  Error err = Call("TpuSharedMemoryStatus", req, &resp, h);
  if (err) return err;
  *status = DecodeShmStatus(resp, /*device_family=*/true);
  return Error::Success();
}

Error InferenceServerGrpcClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle, int device_id,
    size_t byte_size, const Headers& h) {
  std::string req;
  EncodeDeviceShmRegister(name, raw_handle, device_id, byte_size, &req);
  std::string resp;
  return Call("TpuSharedMemoryRegister", req, &resp, h);
}

Error InferenceServerGrpcClient::UnregisterTpuSharedMemory(
    const std::string& name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  std::string resp;
  return Call("TpuSharedMemoryUnregister", req, &resp, h);
}

Error InferenceServerGrpcClient::CudaSharedMemoryStatus(
    Json* status, const std::string& name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  std::string resp;
  Error err = Call("CudaSharedMemoryStatus", req, &resp, h);
  if (err) return err;
  *status = DecodeShmStatus(resp, /*device_family=*/true);
  return Error::Success();
}

Error InferenceServerGrpcClient::RegisterCudaSharedMemory(
    const std::string& name, const std::string& raw_handle, int device_id,
    size_t byte_size, const Headers& h) {
  std::string req;
  EncodeDeviceShmRegister(name, raw_handle, device_id, byte_size, &req);
  std::string resp;
  return Call("CudaSharedMemoryRegister", req, &resp, h);
}

Error InferenceServerGrpcClient::UnregisterCudaSharedMemory(
    const std::string& name, const Headers& h) {
  std::string req;
  pb::Writer w(&req);
  w.String(1, name);
  std::string resp;
  return Call("CudaSharedMemoryUnregister", req, &resp, h);
}

// -- inference --------------------------------------------------------------

Error InferenceServerGrpcClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, const std::string& compression_algorithm) {
  RequestTimers timers;
  timers.Capture(RequestTimers::Kind::REQUEST_START);
  std::string request = EncodeInferRequest(options, inputs, outputs);
  timers.Capture(RequestTimers::Kind::SEND_START);
  std::string response;
  Error err = Call(
      "ModelInfer", request, &response, headers, options.client_timeout_us,
      compression_algorithm.empty() ? DefaultCompression()
                                    : compression_algorithm);
  timers.Capture(RequestTimers::Kind::SEND_END);
  timers.Capture(RequestTimers::Kind::RECV_START);
  if (err) {
    InferResultGrpc::Create(result, std::string(), err);
    return err;
  }
  err = InferResultGrpc::Create(result, std::move(response), Error::Success());
  timers.Capture(RequestTimers::Kind::RECV_END);
  timers.Capture(RequestTimers::Kind::REQUEST_END);
  {
    std::lock_guard<std::mutex> lock(stat_mutex_);
    infer_stat_.Update(timers);
  }
  return err;
}

Error InferenceServerGrpcClient::AsyncInfer(
    OnComplete callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, const std::string& compression_algorithm) {
  if (callback == nullptr) return Error("callback must not be null");
  {
    std::lock_guard<std::mutex> lock(queue_mutex_);
    if (!worker_.joinable()) {
      worker_ = std::thread(&InferenceServerGrpcClient::AsyncTransfer, this);
    }
  }
  auto* request = new AsyncRequest();
  request->method = "ModelInfer";
  request->headers = headers;
  request->timeout_us = options.client_timeout_us;
  request->callback = std::move(callback);
  request->timers.Capture(RequestTimers::Kind::REQUEST_START);
  std::string payload = EncodeInferRequest(options, inputs, outputs);
  const std::string compression = compression_algorithm.empty()
                                      ? DefaultCompression()
                                      : compression_algorithm;
  Error frame_err = FrameMaybeCompressed(payload, compression, &request->body);
  if (frame_err) {
    delete request;
    return frame_err;
  }
  if (!compression.empty()) {
    request->headers["grpc-encoding"] = compression;
  }
  {
    std::lock_guard<std::mutex> lock(queue_mutex_);
    pending_.push_back(request);
  }
  queue_cv_.notify_one();
  return Error::Success();
}

namespace {
int64_t NowMsMono() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}
}  // namespace

// Deliver a completed async request: close out timers, fold the exchange
// into infer_stat_, fire the callback, free the request.
void InferenceServerGrpcClient::FinishAsync(
    AsyncRequest* request, InferResult* result) {
  request->timers.Capture(RequestTimers::Kind::RECV_END);
  request->timers.Capture(RequestTimers::Kind::REQUEST_END);
  {
    std::lock_guard<std::mutex> lock(stat_mutex_);
    infer_stat_.Update(request->timers);
  }
  request->callback(result);
  delete request;
}

void InferenceServerGrpcClient::FinishAsyncError(
    AsyncRequest* request, const Error& err) {
  InferResult* result = nullptr;
  InferResultGrpc::Create(&result, std::string(), err);
  FinishAsync(request, result);
}

// Worker thread: a completion-queue pump. Up to max_async_inflight_ RPCs
// ride concurrent streams on ONE dedicated h2 connection (the transport
// multiplexes; StreamWaitAny reaps whichever finishes first), matching the
// reference's grpc completion-queue model (grpc_client.cc:1583-1626) where
// many AsyncInfer RPCs are in flight per client and callback order is
// unguaranteed. Round 2 serialized one RPC at a time here — the sweep's
// native-grpc numbers only scaled by instantiating many clients.
void InferenceServerGrpcClient::AsyncTransfer() {
  std::unique_ptr<h2::Connection> conn;
  struct Inflight {
    AsyncRequest* request;
    int64_t deadline_ms;  // CLOCK_MONOTONIC ms; 0 = no timeout
  };
  std::map<int32_t, Inflight> inflight;

  auto fail_all_inflight = [&](const std::string& why) {
    for (auto& kv : inflight) {
      FinishAsyncError(
          kv.second.request, Error("[StatusCode.UNAVAILABLE] " + why));
    }
    inflight.clear();
    conn.reset();
  };

  while (true) {
    // -- admit queued requests into the in-flight window ------------------
    std::vector<AsyncRequest*> to_open;
    {
      std::unique_lock<std::mutex> lock(queue_mutex_);
      if (pending_.empty() && inflight.empty()) {
        queue_cv_.wait(lock, [this] { return exiting_ || !pending_.empty(); });
        if (pending_.empty() && exiting_) return;
      }
      // bounded by our window AND the peer's SETTINGS_MAX_CONCURRENT_STREAMS
      // (opening past the peer's cap earns RST_STREAM REFUSED_STREAM)
      size_t window = max_async_inflight_;
      if (conn != nullptr) {
        int64_t peer_cap = conn->PeerMaxConcurrentStreams();
        if (peer_cap > 0 && static_cast<int64_t>(window) > peer_cap) {
          window = static_cast<size_t>(peer_cap);
        }
      }
      while (!pending_.empty() &&
             inflight.size() + to_open.size() < window) {
        to_open.push_back(pending_.front());
        pending_.pop_front();
      }
    }

    if (!to_open.empty() && (conn == nullptr || !conn->Reusable())) {
      if (conn != nullptr && !inflight.empty()) {
        // Draining (GOAWAY) with streams still in flight — streams at or
        // below last_stream_id may yet complete, and a fresh connection's
        // ids (1,3,5,…) would collide with inflight's keys. Requeue and
        // finish the drain first; the reap path below either delivers the
        // survivors or fails them all and resets conn, so this converges.
        std::lock_guard<std::mutex> lock(queue_mutex_);
        while (!to_open.empty()) {
          pending_.push_front(to_open.back());
          to_open.pop_back();
        }
      } else {
        Error cerr;
        std::unique_ptr<h2::Connection> fresh;
        cerr = h2::Connection::Connect(&fresh, url_, 10000, &ssl_options_);
        if (cerr) {
          for (AsyncRequest* request : to_open) {
            FinishAsyncError(
                request, Error("[StatusCode.UNAVAILABLE] " + cerr.Message()));
          }
          to_open.clear();
        } else {
          conn = std::move(fresh);
        }
      }
    }
    if (conn != nullptr && !to_open.empty()) {
      // re-clamp once the live connection's peer settings are known (the
      // admit loop may have run before the connection existed)
      int64_t peer_cap = conn->PeerMaxConcurrentStreams();
      while (peer_cap > 0 &&
             static_cast<int64_t>(inflight.size() + to_open.size()) >
                 peer_cap &&
             !to_open.empty()) {
        std::lock_guard<std::mutex> lock(queue_mutex_);
        pending_.push_front(to_open.back());
        to_open.pop_back();
      }
    }
    for (AsyncRequest* request : to_open) {
      request->timers.Capture(RequestTimers::Kind::SEND_START);
      int64_t timeout_ms =
          request->timeout_us == 0
              ? 0
              : static_cast<int64_t>((request->timeout_us + 999) / 1000);
      int32_t sid = 0;
      Error err = conn->StreamOpen(
          "/inference.GRPCInferenceService/" + request->method,
          GrpcRequestHeaders(MergedHeaders(request->headers)), &sid);
      if (!err) {
        err = conn->StreamSend(
            sid, request->body.data(), request->body.size(), true, timeout_ms);
      }
      request->timers.Capture(RequestTimers::Kind::SEND_END);
      if (err) {
        if (sid != 0 && conn != nullptr && conn->Alive()) {
          // HEADERS went out but the body failed: reset so the peer (and
          // our streams_ map) drop the half-sent stream
          conn->StreamReset(sid);
        }
        FinishAsyncError(
            request,
            Error(err.Message() == "Deadline Exceeded"
                      ? "[StatusCode.DEADLINE_EXCEEDED] Deadline Exceeded"
                      : "[StatusCode.UNAVAILABLE] " + err.Message()));
        continue;
      }
      request->timers.Capture(RequestTimers::Kind::RECV_START);
      inflight[sid] = Inflight{
          request, timeout_ms == 0 ? 0 : NowMsMono() + timeout_ms};
    }
    if (inflight.empty()) continue;

    // -- reap: wait for any in-flight stream to finish --------------------
    // Bounded wait so newly queued requests are admitted promptly and
    // per-request deadlines stay enforced even with no frame traffic.
    // 5 ms tick: with frame traffic the wait returns immediately, so the
    // tick only gates admission latency when the connection is quiet —
    // a self-pipe in the socket poll would remove even that, at the cost
    // of threading a wakeup fd through the transport.
    int64_t wait_ms = 5;
    int64_t now = NowMsMono();
    for (const auto& kv : inflight) {
      if (kv.second.deadline_ms != 0) {
        wait_ms = std::min(wait_ms, std::max<int64_t>(kv.second.deadline_ms - now, 1));
      }
    }
    std::vector<int32_t> ids;
    ids.reserve(inflight.size());
    for (const auto& kv : inflight) ids.push_back(kv.first);
    int32_t ready = 0;
    Error werr = conn->StreamWaitAny(ids, &ready, wait_ms);
    if (werr) {
      if (werr.Message() == "Deadline Exceeded") {
        // poll tick: expire overdue requests, then admit/reap again
        now = NowMsMono();
        for (auto it = inflight.begin(); it != inflight.end();) {
          if (it->second.deadline_ms != 0 && now >= it->second.deadline_ms) {
            conn->StreamReset(it->first);
            FinishAsyncError(
                it->second.request,
                Error("[StatusCode.DEADLINE_EXCEEDED] Deadline Exceeded"));
            it = inflight.erase(it);
          } else {
            ++it;
          }
        }
        continue;
      }
      fail_all_inflight(werr.Message());
      continue;
    }

    auto it = inflight.find(ready);
    if (it == inflight.end()) continue;  // already reaped/reset
    AsyncRequest* request = it->second.request;
    inflight.erase(it);
    std::string body;
    std::map<std::string, std::string> headers;
    bool closed = false;
    Error rerr;
    while (!closed && !rerr) {
      // the stream is terminal (StreamWaitAny), so this drains buffered
      // DATA + trailers without blocking meaningfully
      rerr = conn->StreamRecv(ready, &body, &headers, &closed, 1000);
    }
    InferResult* result = nullptr;
    if (rerr) {
      InferResultGrpc::Create(
          &result, std::string(),
          Error("[StatusCode.UNAVAILABLE] " + rerr.Message()));
    } else {
      Error status = GrpcStatusToError(headers);
      if (status) {
        InferResultGrpc::Create(&result, std::string(), status);
      } else {
        std::string message;
        Error uerr = UnpackResponse(body, &message, /*allow_empty=*/false);
        if (uerr) {
          InferResultGrpc::Create(&result, std::string(), uerr);
        } else {
          InferResultGrpc::Create(
              &result, std::move(message), Error::Success());
        }
      }
    }
    FinishAsync(request, result);
  }
}

namespace {
Error ValidateMultiSizes(
    size_t request_count, size_t options_count, size_t outputs_count) {
  if (request_count == 0) return Error("empty request list");
  if (options_count != 1 && options_count != request_count) {
    return Error(
        "options size must be 1 (broadcast) or match the request count");
  }
  if (outputs_count != 0 && outputs_count != 1 &&
      outputs_count != request_count) {
    return Error(
        "outputs size must be 0, 1 (broadcast) or match the request count");
  }
  return Error::Success();
}
}  // namespace

Error InferenceServerGrpcClient::InferMulti(
    std::vector<InferResult*>* results, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  Error err = ValidateMultiSizes(inputs.size(), options.size(), outputs.size());
  if (err) return err;
  results->clear();
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    InferResult* result = nullptr;
    err = Infer(&result, opt, inputs[i], outs, headers);
    results->push_back(result);
    if (err) return err;
  }
  return Error::Success();
}

Error InferenceServerGrpcClient::AsyncInferMulti(
    OnMultiComplete callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  Error err = ValidateMultiSizes(inputs.size(), options.size(), outputs.size());
  if (err) return err;
  if (callback == nullptr) return Error("callback must not be null");
  struct MultiState {
    std::mutex mutex;
    std::vector<InferResult*> results;
    size_t remaining;
    OnMultiComplete callback;
  };
  auto state = std::make_shared<MultiState>();
  state->results.resize(inputs.size(), nullptr);
  state->remaining = inputs.size();
  state->callback = std::move(callback);
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    err = AsyncInfer(
        [state, i](InferResult* result) {
          bool done = false;
          {
            std::lock_guard<std::mutex> lock(state->mutex);
            state->results[i] = result;
            done = (--state->remaining == 0);
          }
          if (done) state->callback(state->results);
        },
        opt, inputs[i], outs, headers);
    if (err) return err;
  }
  return Error::Success();
}

// -- bi-di streaming --------------------------------------------------------
// A dedicated h2 connection carries the one ModelStreamInfer stream: the
// send half writes framed ModelInferRequests, the reader thread unframes
// ModelStreamInferResponses and fires the callback (reference
// grpc/_infer_stream.py and grpc_client.cc:1628-1673).

struct InferenceServerGrpcClient::StreamCtx {
  std::unique_ptr<h2::Connection> conn;
  int32_t stream_id = 0;
  OnStreamResponse callback;
  std::thread reader;
  std::atomic<bool> active{true};
  std::mutex send_mutex;
  uint64_t timeout_us = 0;
  std::string compression;  // fixed at StreamOpen (grpc-encoding header)
};

Error InferenceServerGrpcClient::StartStream(
    OnStreamResponse callback, const Headers& headers,
    uint64_t stream_timeout_us) {
  if (callback == nullptr) return Error("callback must not be null");
  std::lock_guard<std::mutex> lock(stream_mutex_);
  if (stream_ != nullptr) {
    return Error(
        "cannot start a stream: one is already active; stop it first");
  }
  auto ctx = std::make_unique<StreamCtx>();
  Error err = h2::Connection::Connect(&ctx->conn, url_, 10000, &ssl_options_);
  if (err) return Error("[StatusCode.UNAVAILABLE] " + err.Message());
  // stream compression is fixed at HEADERS time: the client default governs
  // every message sent on this stream
  ctx->compression = DefaultCompression();
  err = ctx->conn->StreamOpen(
      "/inference.GRPCInferenceService/ModelStreamInfer",
      GrpcRequestHeaders(MergedHeaders(headers), ctx->compression),
      &ctx->stream_id);
  if (err) return Error("[StatusCode.UNAVAILABLE] " + err.Message());
  ctx->callback = std::move(callback);
  ctx->timeout_us = stream_timeout_us;
  stream_ = std::move(ctx);
  stream_->reader = std::thread(&InferenceServerGrpcClient::StreamReader, this);
  return Error::Success();
}

void InferenceServerGrpcClient::StreamReader() {
  StreamCtx* ctx;
  {
    std::lock_guard<std::mutex> lock(stream_mutex_);
    ctx = stream_.get();
  }
  if (ctx == nullptr) return;
  std::string buffer;
  std::map<std::string, std::string> response_headers;
  bool closed = false;
  size_t pos = 0;
  while (ctx->active) {
    // with no user stream timeout, poll on a short deadline so StopStream's
    // active=false is noticed even if the server never half-closes
    const bool polling = ctx->timeout_us == 0;
    Error err = ctx->conn->StreamRecv(
        ctx->stream_id, &buffer, &response_headers, &closed,
        polling ? 500 : static_cast<int64_t>(ctx->timeout_us / 1000));
    if (err) {
      if (polling && err.Message() == "Deadline Exceeded") {
        continue;  // re-check ctx->active
      }
      if (ctx->active) {
        ctx->active = false;
        ctx->callback(
            nullptr, Error("[StatusCode.UNAVAILABLE] " + err.Message()));
      }
      return;
    }
    // deliver every complete message in the buffer
    const uint8_t* payload;
    size_t payload_size;
    bool compressed;
    std::string inflated;
    while (pb::UnframeMessage(buffer, &pos, &payload, &payload_size,
                              &compressed)) {
      if (compressed) {
        Error zerr = ZDecompress(
            std::string(reinterpret_cast<const char*>(payload), payload_size),
            &inflated);
        if (zerr) {
          ctx->active = false;
          ctx->callback(nullptr, zerr);
          return;
        }
        payload = reinterpret_cast<const uint8_t*>(inflated.data());
        payload_size = inflated.size();
      }
      // ModelStreamInferResponse: error_message=1, infer_response=2
      pb::Reader r(payload, payload_size);
      uint32_t field, wt;
      std::string error_message;
      std::string infer_payload;
      while (r.Next(&field, &wt)) {
        if (field == 1) {
          error_message = r.StringVal();
        } else if (field == 2) {
          const uint8_t* d;
          size_t n;
          if (r.LengthDelimited(&d, &n)) {
            infer_payload.assign(reinterpret_cast<const char*>(d), n);
          }
        } else {
          r.Skip(wt);
        }
      }
      if (!error_message.empty()) {
        ctx->callback(nullptr, Error(error_message));
      } else {
        InferResult* result = nullptr;
        InferResultGrpc::Create(
            &result, std::move(infer_payload), Error::Success());
        ctx->callback(result, Error::Success());
      }
    }
    if (pos > 0) {
      buffer.erase(0, pos);
      pos = 0;
    }
    if (closed) {
      // true-status mode: surface the terminal grpc-status to the callback
      Error status = GrpcStatusToError(response_headers);
      if (status && ctx->active) {
        ctx->callback(nullptr, status);
      }
      ctx->active = false;
      return;
    }
  }
}

Error InferenceServerGrpcClient::AsyncStreamInfer(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  std::lock_guard<std::mutex> lock(stream_mutex_);
  if (stream_ == nullptr) {
    return Error("stream not available: call StartStream first");
  }
  if (!stream_->active) {
    return Error("the stream is no longer in a valid state; start a new one");
  }
  std::string payload = EncodeInferRequest(options, inputs, outputs);
  std::string framed;
  Error frame_err = FrameMaybeCompressed(payload, stream_->compression, &framed);
  if (frame_err) return frame_err;
  std::lock_guard<std::mutex> send_lock(stream_->send_mutex);
  Error err = stream_->conn->StreamSend(
      stream_->stream_id, framed.data(), framed.size(), /*end_stream=*/false);
  if (err) {
    stream_->active = false;
    return Error("[StatusCode.UNAVAILABLE] " + err.Message());
  }
  return Error::Success();
}

Error InferenceServerGrpcClient::StopStream() {
  std::unique_ptr<StreamCtx> ctx;
  {
    std::lock_guard<std::mutex> lock(stream_mutex_);
    ctx = std::move(stream_);
  }
  if (ctx == nullptr) return Error::Success();
  // half-close the send side; the server then ends the response stream and
  // the reader exits on END_STREAM. A wedged server cannot hang us: the
  // reader polls on a 500 ms deadline and re-checks active, which flips
  // below before the join.
  if (ctx->conn->Alive()) {
    std::lock_guard<std::mutex> send_lock(ctx->send_mutex);
    ctx->conn->StreamSend(ctx->stream_id, nullptr, 0, /*end_stream=*/true);
  }
  ctx->active = false;
  if (ctx->reader.joinable()) ctx->reader.join();
  if (ctx->conn->Alive()) {
    ctx->conn->StreamReset(ctx->stream_id);  // no-op if already closed
  }
  return Error::Success();
}

InferStat InferenceServerGrpcClient::ClientInferStat() {
  std::lock_guard<std::mutex> lock(stat_mutex_);
  return infer_stat_;
}

}  // namespace client_tpu
