#include "client_tpu/tpu_shm.h"

#include <unistd.h>

#include <cstring>
#include <random>

#include "client_tpu/base64.h"
#include "client_tpu/json.h"
#include "client_tpu/shm_utils.h"

namespace client_tpu {

namespace {
std::string RandomKey() {
  static const char hex[] = "0123456789abcdef";
  std::random_device rd;
  std::string key = "tpushm_";
  for (int i = 0; i < 12; ++i) key.push_back(hex[rd() % 16]);
  return key;
}
}  // namespace

Error TpuShmRegion::Create(
    TpuShmRegion** region, const std::string& name, size_t byte_size,
    int device_id, const std::string& shm_key) {
  auto* r = new TpuShmRegion();
  r->name_ = name;
  r->shm_key_ = shm_key.empty() ? RandomKey() : shm_key;
  r->byte_size_ = byte_size;
  r->device_id_ = device_id;
  r->owned_ = true;
  // multiprocessing.shared_memory uses "/<name>" POSIX keys; match it
  std::string posix_key = "/" + r->shm_key_;
  Error err = CreateSharedMemoryRegion(posix_key, byte_size, &r->fd_);
  if (err) {
    delete r;
    return err;
  }
  err = MapSharedMemory(r->fd_, 0, byte_size, &r->addr_);
  if (err) {
    CloseSharedMemory(r->fd_);
    UnlinkSharedMemoryRegion(posix_key);
    delete r;
    return err;
  }
  *region = r;
  return Error::Success();
}

Error TpuShmRegion::Attach(TpuShmRegion** region, const std::string& raw_handle) {
  std::vector<uint8_t> decoded;
  if (!Base64Decode(raw_handle, &decoded)) {
    return Error("invalid tpu shared-memory raw handle: not base64");
  }
  Json desc;
  std::string parse_error;
  if (!Json::Parse(
          std::string(decoded.begin(), decoded.end()), &desc, &parse_error)) {
    return Error("invalid tpu shared-memory raw handle: " + parse_error);
  }
  auto* r = new TpuShmRegion();
  r->shm_key_ = desc.At("shm_key").AsString();
  r->name_ = desc.Has("name") ? desc.At("name").AsString() : r->shm_key_;
  r->byte_size_ = static_cast<size_t>(desc.At("byte_size").AsInt());
  r->device_id_ = static_cast<int>(desc.At("device_id").AsInt());
  r->owned_ = false;
  std::string posix_key = "/" + r->shm_key_;
  Error err = OpenSharedMemoryRegion(posix_key, &r->fd_);
  if (err) {
    delete r;
    return err;
  }
  err = MapSharedMemory(r->fd_, 0, r->byte_size_, &r->addr_);
  if (err) {
    CloseSharedMemory(r->fd_);
    delete r;
    return err;
  }
  *region = r;
  return Error::Success();
}

TpuShmRegion::~TpuShmRegion() {
  if (addr_ != nullptr) UnmapSharedMemory(addr_, byte_size_);
  if (fd_ != -1) CloseSharedMemory(fd_);
  if (owned_) UnlinkSharedMemoryRegion("/" + shm_key_);
}

std::string TpuShmRegion::RawHandle() const {
  Json desc = Json::Object();
  desc.Set("kind", Json("tpu_shared_memory"));
  desc.Set("shm_key", Json(shm_key_));
  desc.Set("byte_size", Json(static_cast<int64_t>(byte_size_)));
  desc.Set("device_id", Json(static_cast<int64_t>(device_id_)));
  desc.Set("colocated", Json(false));
  std::string text = desc.Dump();
  return Base64Encode(text);
}

Error TpuShmRegion::Write(const void* src, size_t byte_size, size_t offset) {
  // overflow-safe: offset + byte_size could wrap for hostile offsets
  if (offset > byte_size_ || byte_size > byte_size_ - offset) {
    return Error("tpu shared-memory write exceeds region size");
  }
  std::memcpy(Data() + offset, src, byte_size);
  return Error::Success();
}

Error TpuShmRegion::Read(void* dst, size_t byte_size, size_t offset) const {
  if (offset > byte_size_ || byte_size > byte_size_ - offset) {
    return Error("tpu shared-memory read exceeds region size");
  }
  std::memcpy(dst, Data() + offset, byte_size);
  return Error::Success();
}

}  // namespace client_tpu
