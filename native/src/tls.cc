// TLS over the system libssl.so.3 runtime, resolved via dlopen (see tls.h
// for why there is no build-time OpenSSL dependency in this image).
//
// ABI notes: every entry point used here has had a stable signature since
// OpenSSL 1.1.0 and is unchanged in 3.x; constants (SSL_ERROR_*,
// SSL_VERIFY_*, SSL_FILETYPE_PEM, SSL_CTRL_SET_TLSEXT_HOSTNAME) are
// likewise ABI-frozen — they are redeclared below from the public spec.

#include "client_tpu/tls.h"

#include <dlfcn.h>
#include <errno.h>
#include <poll.h>
#include <signal.h>

#include <mutex>

namespace client_tpu {
namespace tls {

namespace {

// -- libssl ABI (hand-declared; no headers in the image) --------------------
constexpr int kSslErrorWantRead = 2;   // SSL_ERROR_WANT_READ
constexpr int kSslErrorWantWrite = 3;  // SSL_ERROR_WANT_WRITE
constexpr int kSslErrorZeroReturn = 6; // SSL_ERROR_ZERO_RETURN
constexpr int kSslVerifyNone = 0;      // SSL_VERIFY_NONE
constexpr int kSslVerifyPeer = 1;      // SSL_VERIFY_PEER
constexpr int kSslFiletypePem = 1;     // SSL_FILETYPE_PEM
constexpr int kCtrlSetTlsextHostname = 55;  // SSL_CTRL_SET_TLSEXT_HOSTNAME

struct Libssl {
  void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(void*);
  void (*SSL_CTX_free)(void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
  int (*SSL_CTX_set_alpn_protos)(void*, const unsigned char*, unsigned);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_get_error)(const void*, int);
  int (*SSL_shutdown)(void*);
  long (*SSL_ctrl)(void*, int, long, void*);
  int (*SSL_set1_host)(void*, const char*);
  void (*SSL_get0_alpn_selected)(const void*, const unsigned char**, unsigned*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);
  bool ok = false;
};

Libssl* Load() {
  static Libssl lib;
  static std::once_flag once;
  std::call_once(once, [] {
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) ssl = dlopen("libssl.so.1.1", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) return;
    void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (crypto == nullptr) crypto = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_GLOBAL);
    auto sym = [&](const char* name) -> void* {
      void* p = dlsym(ssl, name);
      if (p == nullptr && crypto != nullptr) p = dlsym(crypto, name);
      return p;
    };
#define RESOLVE(name)                                      \
  lib.name = reinterpret_cast<decltype(lib.name)>(sym(#name)); \
  if (lib.name == nullptr) return;
    RESOLVE(TLS_client_method)
    RESOLVE(SSL_CTX_new)
    RESOLVE(SSL_CTX_free)
    RESOLVE(SSL_CTX_set_verify)
    RESOLVE(SSL_CTX_load_verify_locations)
    RESOLVE(SSL_CTX_set_default_verify_paths)
    RESOLVE(SSL_CTX_use_certificate_chain_file)
    RESOLVE(SSL_CTX_use_PrivateKey_file)
    RESOLVE(SSL_CTX_set_alpn_protos)
    RESOLVE(SSL_new)
    RESOLVE(SSL_free)
    RESOLVE(SSL_set_fd)
    RESOLVE(SSL_connect)
    RESOLVE(SSL_read)
    RESOLVE(SSL_write)
    RESOLVE(SSL_get_error)
    RESOLVE(SSL_shutdown)
    RESOLVE(SSL_ctrl)
    RESOLVE(SSL_set1_host)
    RESOLVE(SSL_get0_alpn_selected)
    RESOLVE(ERR_get_error)
    RESOLVE(ERR_error_string_n)
#undef RESOLVE
    // SSL_write cannot pass MSG_NOSIGNAL to the underlying write(2) (unlike
    // the plaintext path, h2.cc SendAll); a peer-closed TLS socket would
    // SIGPIPE-kill the host. Ignore SIGPIPE iff the host left it at SIG_DFL
    // (Python and most servers already ignore it; we never override a
    // handler the host installed).
    struct sigaction current;
    if (sigaction(SIGPIPE, nullptr, &current) == 0 &&
        current.sa_handler == SIG_DFL) {
      struct sigaction ignore = {};
      ignore.sa_handler = SIG_IGN;
      sigaction(SIGPIPE, &ignore, nullptr);
    }
    lib.ok = true;
  });
  return lib.ok ? &lib : nullptr;
}

std::string LastSslError(Libssl* lib) {
  unsigned long code = lib->ERR_get_error();
  if (code == 0) return "unknown TLS error";
  char buf[256];
  lib->ERR_error_string_n(code, buf, sizeof(buf));
  return std::string(buf);
}

int64_t NowMs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

}  // namespace

bool Available() { return Load() != nullptr; }

Error TlsSession::Create(
    std::unique_ptr<TlsSession>* out, int fd, const std::string& host,
    const TlsOptions& options, int64_t timeout_ms) {
  Libssl* lib = Load();
  if (lib == nullptr) {
    return Error("TLS unavailable: system libssl runtime not found");
  }
  std::unique_ptr<TlsSession> session(new TlsSession());
  session->ctx_ = lib->SSL_CTX_new(lib->TLS_client_method());
  if (session->ctx_ == nullptr) return Error("SSL_CTX_new failed");

  if (options.verify_peer) {
    lib->SSL_CTX_set_verify(session->ctx_, kSslVerifyPeer, nullptr);
    if (!options.ca_cert_file.empty()) {
      if (lib->SSL_CTX_load_verify_locations(
              session->ctx_, options.ca_cert_file.c_str(), nullptr) != 1) {
        return Error("failed to load CA bundle '" + options.ca_cert_file +
                     "': " + LastSslError(lib));
      }
    } else {
      lib->SSL_CTX_set_default_verify_paths(session->ctx_);
    }
  } else {
    lib->SSL_CTX_set_verify(session->ctx_, kSslVerifyNone, nullptr);
  }
  if (!options.client_cert_file.empty()) {
    if (lib->SSL_CTX_use_certificate_chain_file(
            session->ctx_, options.client_cert_file.c_str()) != 1) {
      return Error("failed to load client certificate: " + LastSslError(lib));
    }
    const std::string& key = options.client_key_file.empty()
                                 ? options.client_cert_file
                                 : options.client_key_file;
    if (lib->SSL_CTX_use_PrivateKey_file(
            session->ctx_, key.c_str(), kSslFiletypePem) != 1) {
      return Error("failed to load client key: " + LastSslError(lib));
    }
  }
  // Offer h2 first (gRPC), http/1.1 second (plain HTTPS servers).
  static const unsigned char kAlpn[] = {2, 'h', '2', 8, 'h', 't', 't', 'p',
                                        '/', '1', '.', '1'};
  lib->SSL_CTX_set_alpn_protos(session->ctx_, kAlpn, sizeof(kAlpn));

  session->ssl_ = lib->SSL_new(session->ctx_);
  if (session->ssl_ == nullptr) return Error("SSL_new failed");
  lib->SSL_set_fd(session->ssl_, fd);
  // SNI (SSL_set_tlsext_host_name is an SSL_ctrl macro in the headers)
  lib->SSL_ctrl(session->ssl_, kCtrlSetTlsextHostname, 0,
                const_cast<char*>(host.c_str()));
  if (options.verify_peer && options.verify_host) {
    lib->SSL_set1_host(session->ssl_, host.c_str());
  }

  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  while (true) {
    int rc = lib->SSL_connect(session->ssl_);
    if (rc == 1) break;
    int err = lib->SSL_get_error(session->ssl_, rc);
    if (err != kSslErrorWantRead && err != kSslErrorWantWrite) {
      return Error("TLS handshake with " + host + " failed: " +
                   LastSslError(lib));
    }
    struct pollfd pfd = {fd, static_cast<short>(
                                 err == kSslErrorWantRead ? POLLIN : POLLOUT),
                         0};
    int wait = deadline ? static_cast<int>(deadline - NowMs()) : 1000;
    if (deadline && wait <= 0) return Error("TLS handshake timeout");
    poll(&pfd, 1, wait);
  }
  const unsigned char* proto = nullptr;
  unsigned proto_len = 0;
  lib->SSL_get0_alpn_selected(session->ssl_, &proto, &proto_len);
  if (proto != nullptr) {
    session->alpn_.assign(reinterpret_cast<const char*>(proto), proto_len);
  }
  *out = std::move(session);
  return Error::Success();
}

TlsSession::~TlsSession() {
  Libssl* lib = Load();
  if (lib != nullptr) {
    if (ssl_ != nullptr) {
      lib->SSL_shutdown(ssl_);  // best-effort close_notify (non-blocking fd)
      lib->SSL_free(ssl_);
    }
    if (ctx_ != nullptr) lib->SSL_CTX_free(ctx_);
  }
}

ssize_t TlsSession::Send(const void* data, size_t size) {
  Libssl* lib = Load();
  std::lock_guard<std::mutex> lock(io_mutex_);
  int rc = lib->SSL_write(ssl_, data, static_cast<int>(size));
  if (rc > 0) return rc;
  int err = lib->SSL_get_error(ssl_, rc);
  if (err == kSslErrorWantRead || err == kSslErrorWantWrite) {
    send_poll_events_ = err == kSslErrorWantRead ? POLLIN : POLLOUT;
    errno = EAGAIN;
    return -1;
  }
  errno = ECONNRESET;
  return -1;
}

ssize_t TlsSession::Recv(void* buf, size_t size) {
  Libssl* lib = Load();
  std::lock_guard<std::mutex> lock(io_mutex_);
  int rc = lib->SSL_read(ssl_, buf, static_cast<int>(size));
  if (rc > 0) return rc;
  int err = lib->SSL_get_error(ssl_, rc);
  if (err == kSslErrorZeroReturn) return 0;  // orderly TLS close
  if (err == kSslErrorWantRead || err == kSslErrorWantWrite) {
    recv_poll_events_ = err == kSslErrorWantRead ? POLLIN : POLLOUT;
    errno = EAGAIN;
    return -1;
  }
  errno = ECONNRESET;
  return -1;
}

}  // namespace tls
}  // namespace client_tpu
