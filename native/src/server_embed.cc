// CPython-embedding implementation of server_embed.h.
//
// Design: one embedded interpreter, initialized once; every API call takes
// the GIL (PyGILState_Ensure) and calls a function in
// client_tpu.server.embed, converting results to C buffers the caller
// frees with ctpu_embed_free(). No Python object outlives a call except
// the cached module reference.
//
// Reference parity: the tritonserver C API surface java-api-bindings wraps
// (TRITONSERVER_ServerNew / InferenceRequest / ...) maps here to
// create/infer/metadata/load/unload/destroy with the v2 body contract
// replacing the C tensor-attribute calls — the embedding host reuses the
// same marshaling code every client in this repo already has.

#include "client_tpu/server_embed.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_init_mutex;
bool g_initialized = false;
PyObject* g_embed_module = nullptr;  // client_tpu.server.embed
PyThreadState* g_main_tstate = nullptr;

char* DupString(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out != nullptr) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

void SetError(char** error, const std::string& message) {
  if (error != nullptr) *error = DupString(message);
}

// Fetch the pending Python exception as "Type: message" (GIL held).
std::string FetchPyError() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  std::string message = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) message = c;
      Py_DECREF(s);
    }
  }
  if (type != nullptr) {
    PyObject* n = PyObject_GetAttrString(type, "__name__");
    if (n != nullptr) {
      const char* c = PyUnicode_AsUTF8(n);
      if (c != nullptr) message = std::string(c) + ": " + message;
      Py_DECREF(n);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return message;
}

// RAII GIL acquisition for API calls (interpreter must be initialized).
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// Call embed.<fn>(*args); returns new reference or nullptr (error set).
PyObject* CallEmbed(const char* fn, PyObject* args) {
  PyObject* callable = PyObject_GetAttrString(g_embed_module, fn);
  if (callable == nullptr) return nullptr;
  PyObject* result = PyObject_CallObject(callable, args);
  Py_DECREF(callable);
  return result;
}

}  // namespace

extern "C" {

int ctpu_embed_init(const char* repo_path, char** error) {
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (g_initialized) return 0;
  // Two hosting modes: a plain C/C++/Java process (we own the interpreter)
  // or a Python process that dlopened this library (we must not re-init and
  // must take the GIL before touching anything).
  bool created = false;
  if (!Py_IsInitialized()) {
    PyConfig config;
    PyConfig_InitPythonConfig(&config);
    PyStatus status = Py_InitializeFromConfig(&config);
    PyConfig_Clear(&config);
    if (PyStatus_Exception(status)) {
      SetError(error, std::string("interpreter init failed: ") +
                          (status.err_msg != nullptr ? status.err_msg : "?"));
      return 1;
    }
    created = true;
  }
  {
    PyGILState_STATE st = PyGILState_Ensure();
    if (repo_path != nullptr && repo_path[0] != '\0') {
      PyObject* sys_path = PySys_GetObject("path");  // borrowed
      PyObject* p = PyUnicode_FromString(repo_path);
      if (sys_path != nullptr && p != nullptr) PyList_Insert(sys_path, 0, p);
      Py_XDECREF(p);
    }
    g_embed_module = PyImport_ImportModule("client_tpu.server.embed");
    const bool import_failed = g_embed_module == nullptr;
    if (import_failed) {
      SetError(error, "import client_tpu.server.embed failed: " +
                          FetchPyError());
    }
    PyGILState_Release(st);
    if (import_failed) {
      if (created) {
        // release the init thread's GIL even on failure: a retry (or any
        // other caller) must be able to PyGILState_Ensure, not deadlock
        g_main_tstate = PyEval_SaveThread();
      }
      return 1;
    }
  }
  if (created) {
    // we initialized in this thread and still hold its GIL: release it so
    // ctpu_embed_* can PyGILState_Ensure from any thread
    g_main_tstate = PyEval_SaveThread();
  }
  g_initialized = true;
  return 0;
}

int64_t ctpu_embed_server_create(const char* options_json, char** error) {
  if (!g_initialized) {
    int rc = ctpu_embed_init(nullptr, error);
    if (rc != 0) return 0;
  }
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(s)", options_json != nullptr ? options_json : "");
  PyObject* result = CallEmbed("create", args);
  Py_XDECREF(args);
  if (result == nullptr) {
    SetError(error, FetchPyError());
    return 0;
  }
  int64_t handle = PyLong_AsLongLong(result);
  Py_DECREF(result);
  if (handle <= 0) {
    PyErr_Clear();  // a stale pending exception would poison the next call
    SetError(error, "embed.create returned an invalid handle");
    return 0;
  }
  return handle;
}

int ctpu_embed_infer(
    int64_t server, const char* model_name, const char* model_version,
    const uint8_t* body, size_t body_len, int64_t header_length,
    uint8_t** response, size_t* response_len, int64_t* response_header_len,
    char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Lssy#L)", static_cast<long long>(server),
      model_name != nullptr ? model_name : "",
      model_version != nullptr ? model_version : "",
      reinterpret_cast<const char*>(body), static_cast<Py_ssize_t>(body_len),
      static_cast<long long>(header_length));
  PyObject* result = args != nullptr ? CallEmbed("infer", args) : nullptr;
  Py_XDECREF(args);
  if (result == nullptr) {
    SetError(error, FetchPyError());
    return 1;
  }
  // result: (bytes, header_len)
  PyObject* payload = PyTuple_GetItem(result, 0);    // borrowed
  PyObject* header_len = PyTuple_GetItem(result, 1); // borrowed
  if (payload == nullptr || header_len == nullptr) {
    PyErr_Clear();  // IndexError/SystemError from GetItem must not leak
    Py_DECREF(result);
    SetError(error, "embed.infer returned a malformed tuple");
    return 1;
  }
  char* data = nullptr;
  Py_ssize_t size = 0;
  if (PyBytes_AsStringAndSize(payload, &data, &size) != 0) {
    Py_DECREF(result);
    SetError(error, FetchPyError());
    return 1;
  }
  int64_t hlen = PyLong_AsLongLong(header_len);
  if (hlen == -1 && PyErr_Occurred()) {
    PyErr_Clear();
    Py_DECREF(result);
    SetError(error, "embed.infer returned a non-integer header length");
    return 1;
  }
  uint8_t* out = static_cast<uint8_t*>(std::malloc(size > 0 ? size : 1));
  if (out == nullptr) {
    Py_DECREF(result);
    SetError(error, "out of memory copying response");
    return 1;
  }
  std::memcpy(out, data, size);
  *response = out;
  *response_len = static_cast<size_t>(size);
  *response_header_len = hlen;
  Py_DECREF(result);
  return 0;
}

namespace {

// Shared shape of the JSON-returning admin calls.
int JsonCall(const char* fn, PyObject* args, char** json, char** error) {
  Gil gil;
  PyObject* result = args != nullptr ? CallEmbed(fn, args) : nullptr;
  Py_XDECREF(args);
  if (result == nullptr) {
    SetError(error, FetchPyError());
    return 1;
  }
  char* data = nullptr;
  Py_ssize_t size = 0;
  if (PyBytes_AsStringAndSize(result, &data, &size) != 0) {
    Py_DECREF(result);
    SetError(error, FetchPyError());
    return 1;
  }
  char* out = static_cast<char*>(std::malloc(size + 1));
  if (out == nullptr) {
    Py_DECREF(result);
    SetError(error, "out of memory copying json");
    return 1;
  }
  std::memcpy(out, data, size);
  out[size] = '\0';
  *json = out;
  Py_DECREF(result);
  return 0;
}

}  // namespace

int ctpu_embed_metadata(
    int64_t server, const char* model_name, char** json, char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil_for_build;  // Py_BuildValue needs the GIL too
  PyObject* args = Py_BuildValue(
      "(Ls)", static_cast<long long>(server),
      model_name != nullptr ? model_name : "");
  return JsonCall("metadata_json", args, json, error);
}

int ctpu_embed_repository_index(int64_t server, char** json, char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil_for_build;
  PyObject* args = Py_BuildValue("(L)", static_cast<long long>(server));
  return JsonCall("repository_index_json", args, json, error);
}

int ctpu_embed_statistics(
    int64_t server, const char* model_name, char** json, char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil_for_build;
  PyObject* args = Py_BuildValue(
      "(Ls)", static_cast<long long>(server),
      model_name != nullptr ? model_name : "");
  return JsonCall("statistics_json", args, json, error);
}

namespace {

// Shared shape of the None-returning lifecycle calls.
int VoidCall(const char* fn, PyObject* args, char** error) {
  Gil gil;
  PyObject* result = args != nullptr ? CallEmbed(fn, args) : nullptr;
  Py_XDECREF(args);
  if (result == nullptr) {
    SetError(error, FetchPyError());
    return 1;
  }
  Py_DECREF(result);
  return 0;
}

}  // namespace

int ctpu_embed_load_model(
    int64_t server, const char* model_name, const char* config_json,
    char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil_for_build;
  PyObject* args = Py_BuildValue(
      "(Lss)", static_cast<long long>(server),
      model_name != nullptr ? model_name : "",
      config_json != nullptr ? config_json : "");
  return VoidCall("load_model", args, error);
}

int ctpu_embed_unload_model(
    int64_t server, const char* model_name, char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil_for_build;
  PyObject* args = Py_BuildValue(
      "(Ls)", static_cast<long long>(server),
      model_name != nullptr ? model_name : "");
  return VoidCall("unload_model", args, error);
}

int ctpu_embed_start_http(int64_t server, int* port, char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil;
  PyObject* args = Py_BuildValue(
      "(Li)", static_cast<long long>(server), port != nullptr ? *port : 0);
  PyObject* result = args != nullptr ? CallEmbed("start_http", args) : nullptr;
  Py_XDECREF(args);
  if (result == nullptr) {
    SetError(error, FetchPyError());
    return 1;
  }
  if (port != nullptr) *port = static_cast<int>(PyLong_AsLong(result));
  Py_DECREF(result);
  return 0;
}

int ctpu_embed_server_destroy(int64_t server, char** error) {
  if (!g_initialized) {
    SetError(error, "not initialized");
    return 1;
  }
  Gil gil;
  PyObject* args = Py_BuildValue("(L)", static_cast<long long>(server));
  return VoidCall("destroy", args, error);
}

void ctpu_embed_free(void* ptr) { std::free(ptr); }

}  // extern "C"
