#include "client_tpu/http_client.h"

#include <curl/curl.h>

#include <cstring>
#include <sstream>

#include "client_tpu/base64.h"

namespace client_tpu {

namespace {

// Process-wide curl lifecycle (reference http_client.cc:71-101).
struct CurlGlobal {
  CurlGlobal() { curl_global_init(CURL_GLOBAL_ALL); }
  ~CurlGlobal() { curl_global_cleanup(); }
};
static CurlGlobal curl_global;

size_t WriteBody(char* ptr, size_t size, size_t nmemb, void* userdata) {
  auto* out = static_cast<std::string*>(userdata);
  out->append(ptr, size * nmemb);
  return size * nmemb;
}

struct HeaderCapture {
  long header_length = -1;
};

size_t WriteHeader(char* ptr, size_t size, size_t nmemb, void* userdata) {
  auto* capture = static_cast<HeaderCapture*>(userdata);
  std::string line(ptr, size * nmemb);
  const std::string key = "Inference-Header-Content-Length:";
  if (line.size() > key.size() &&
      strncasecmp(line.c_str(), key.c_str(), key.size()) == 0) {
    capture->header_length = strtol(line.c_str() + key.size(), nullptr, 10);
  }
  return size * nmemb;
}

Error ErrorFromResponse(long http_code, const std::string& body) {
  if (http_code < 400) return Error::Success();
  Json parsed;
  std::string perr;
  if (Json::Parse(body, &parsed, &perr) && parsed.Has("error")) {
    return Error(
        "[" + std::to_string(http_code) + "] " + parsed.At("error").AsString());
  }
  return Error("[" + std::to_string(http_code) + "] " + body);
}

void AppendShmParams(
    Json* params, const std::string& region, size_t byte_size, size_t offset) {
  params->Set("shared_memory_region", Json(region));
  params->Set(
      "shared_memory_byte_size", Json(static_cast<int64_t>(byte_size)));
  if (offset != 0) {
    params->Set("shared_memory_offset", Json(static_cast<int64_t>(offset)));
  }
}

// Builds the two-part body; returns the JSON header length.
size_t BuildInferBody(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    std::string* body) {
  Json header = Json::Object();
  if (!options.request_id.empty()) {
    header.Set("id", Json(options.request_id));
  }
  Json params = Json::Object();
  if (options.sequence_id != 0 || !options.sequence_id_str.empty()) {
    if (!options.sequence_id_str.empty()) {
      params.Set("sequence_id", Json(options.sequence_id_str));
    } else {
      params.Set(
          "sequence_id", Json(static_cast<int64_t>(options.sequence_id)));
    }
    params.Set("sequence_start", Json(options.sequence_start));
    params.Set("sequence_end", Json(options.sequence_end));
  }
  if (options.priority != 0) {
    params.Set("priority", Json(static_cast<int64_t>(options.priority)));
  }
  if (options.server_timeout_us != 0) {
    params.Set(
        "timeout", Json(static_cast<int64_t>(options.server_timeout_us)));
  }
  for (const auto& kv : options.request_parameters) {
    params.Set(kv.first, Json(kv.second));
  }
  if (outputs.empty()) {
    params.Set("binary_data_output", Json(true));
  }
  if (!params.items().empty()) {
    header.Set("parameters", std::move(params));
  }

  Json inputs_json = Json::Array();
  for (const auto* input : inputs) {
    Json tensor = Json::Object();
    tensor.Set("name", Json(input->Name()));
    tensor.Set("datatype", Json(input->Datatype()));
    Json shape = Json::Array();
    for (int64_t d : input->Shape()) {
      shape.Append(Json(static_cast<int64_t>(d)));
    }
    tensor.Set("shape", std::move(shape));
    Json tparams = Json::Object();
    if (input->InSharedMemory()) {
      AppendShmParams(
          &tparams, input->SharedMemoryRegion(), input->SharedMemoryByteSize(),
          input->SharedMemoryOffset());
    } else {
      tparams.Set(
          "binary_data_size", Json(static_cast<int64_t>(input->ByteSize())));
    }
    tensor.Set("parameters", std::move(tparams));
    inputs_json.Append(std::move(tensor));
  }
  header.Set("inputs", std::move(inputs_json));

  if (!outputs.empty()) {
    Json outputs_json = Json::Array();
    for (const auto* output : outputs) {
      Json tensor = Json::Object();
      tensor.Set("name", Json(output->Name()));
      Json oparams = Json::Object();
      if (output->InSharedMemory()) {
        AppendShmParams(
            &oparams, output->SharedMemoryRegion(),
            output->SharedMemoryByteSize(), output->SharedMemoryOffset());
      } else {
        oparams.Set("binary_data", Json(output->BinaryData()));
      }
      if (output->ClassCount() != 0) {
        oparams.Set(
            "classification",
            Json(static_cast<int64_t>(output->ClassCount())));
      }
      tensor.Set("parameters", std::move(oparams));
      outputs_json.Append(std::move(tensor));
    }
    header.Set("outputs", std::move(outputs_json));
  }

  std::string header_text = header.Dump();
  size_t header_length = header_text.size();
  size_t total = header_length;
  for (const auto* input : inputs) total += input->ByteSize();
  body->clear();
  body->reserve(total);
  body->append(header_text);
  for (const auto* input : inputs) {
    for (const auto& buf : input->Buffers()) {
      body->append(reinterpret_cast<const char*>(buf.first), buf.second);
    }
  }
  return header_length;
}

// Decodes a JSON "data" array into the little-endian wire representation so
// non-binary outputs are readable through the same RawData accessor.
bool DecodeJsonData(
    const Json& data, const std::string& datatype, std::string* buf) {
  auto append = [&](const void* p, size_t n) {
    buf->append(static_cast<const char*>(p), n);
  };
  if (datatype == "BYTES") {
    std::vector<std::string> strings;
    for (size_t i = 0; i < data.size(); ++i) {
      strings.push_back(data[i].AsString());
    }
    SerializeStrings(strings, buf);
    return true;
  }
  for (size_t i = 0; i < data.size(); ++i) {
    const Json& v = data[i];
    if (datatype == "BOOL") {
      uint8_t b = v.AsBool() ? 1 : 0;
      append(&b, 1);
    } else if (datatype == "INT8") {
      int8_t x = static_cast<int8_t>(v.AsInt());
      append(&x, 1);
    } else if (datatype == "INT16") {
      int16_t x = static_cast<int16_t>(v.AsInt());
      append(&x, 2);
    } else if (datatype == "INT32") {
      int32_t x = static_cast<int32_t>(v.AsInt());
      append(&x, 4);
    } else if (datatype == "INT64") {
      int64_t x = v.AsInt();
      append(&x, 8);
    } else if (datatype == "UINT8") {
      uint8_t x = static_cast<uint8_t>(v.AsInt());
      append(&x, 1);
    } else if (datatype == "UINT16") {
      uint16_t x = static_cast<uint16_t>(v.AsInt());
      append(&x, 2);
    } else if (datatype == "UINT32") {
      uint32_t x = static_cast<uint32_t>(v.AsInt());
      append(&x, 4);
    } else if (datatype == "UINT64") {
      uint64_t x = static_cast<uint64_t>(v.AsInt());
      append(&x, 8);
    } else if (datatype == "FP32") {
      float x = static_cast<float>(v.AsDouble());
      append(&x, 4);
    } else if (datatype == "FP64") {
      double x = v.AsDouble();
      append(&x, 8);
    } else {
      return false;  // FP16/BF16 have no JSON representation
    }
  }
  return true;
}

class InferResultHttp : public InferResult {
 public:
  static Error Create(
      InferResult** result, std::string&& body, long header_length,
      long http_code) {
    auto* r = new InferResultHttp();
    r->body_ = std::move(body);
    r->status_ = ErrorFromResponse(http_code, r->body_);
    if (!r->status_) {
      size_t json_size =
          header_length >= 0 ? static_cast<size_t>(header_length)
                             : r->body_.size();
      if (json_size > r->body_.size()) {
        r->status_ = Error(
            "malformed inference response: header length exceeds the body");
        *result = r;
        return Error::Success();
      }
      std::string perr;
      if (!Json::Parse(r->body_.substr(0, json_size), &r->header_, &perr)) {
        r->status_ = Error("failed to parse inference response: " + perr);
      } else {
        size_t cursor = json_size;
        const Json& outs = r->header_.At("outputs");
        for (size_t i = 0; i < outs.size(); ++i) {
          const Json& out = outs[i];
          const Json& params = out.At("parameters");
          const std::string name = out.At("name").AsString();
          if (params.Has("binary_data_size")) {
            int64_t declared = params.At("binary_data_size").AsInt();
            if (declared < 0 ||
                cursor + static_cast<size_t>(declared) > r->body_.size()) {
              r->status_ = Error(
                  "malformed inference response: output '" + name +
                  "' declares binary bytes beyond the body");
              break;
            }
            size_t size = static_cast<size_t>(declared);
            r->offsets_[name] = {cursor, size};
            cursor += size;
          } else if (out.Has("data")) {
            // JSON-mode output: decode into an owned buffer so RawData works
            std::string decoded;
            if (DecodeJsonData(
                    out.At("data"), out.At("datatype").AsString(), &decoded)) {
              r->json_buffers_[name] = std::move(decoded);
            }
          }
        }
      }
    }
    *result = r;
    return Error::Success();
  }

  Error ModelName(std::string* name) const override {
    *name = header_.At("model_name").AsString();
    return Error::Success();
  }
  Error ModelVersion(std::string* version) const override {
    *version = header_.At("model_version").AsString();
    return Error::Success();
  }
  Error Id(std::string* id) const override {
    *id = header_.At("id").AsString();
    return Error::Success();
  }

  Error OutputNames(std::vector<std::string>* names) const override {
    names->clear();
    const Json& outs = header_.At("outputs");
    for (size_t i = 0; i < outs.size(); ++i) {
      names->push_back(outs[i].At("name").AsString());
    }
    return Error::Success();
  }

  const Json* FindOutput(const std::string& name) const {
    const Json& outs = header_.At("outputs");
    for (size_t i = 0; i < outs.size(); ++i) {
      if (outs[i].At("name").AsString() == name) return &outs[i];
    }
    return nullptr;
  }

  Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const override {
    const Json* out = FindOutput(output_name);
    if (out == nullptr) return Error("output '" + output_name + "' not found");
    const Json& dims = out->At("shape");
    shape->clear();
    for (size_t i = 0; i < dims.size(); ++i) shape->push_back(dims[i].AsInt());
    return Error::Success();
  }

  Error Datatype(
      const std::string& output_name, std::string* datatype) const override {
    const Json* out = FindOutput(output_name);
    if (out == nullptr) return Error("output '" + output_name + "' not found");
    *datatype = out->At("datatype").AsString();
    return Error::Success();
  }

  Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const override {
    auto it = offsets_.find(output_name);
    if (it != offsets_.end()) {
      *buf = reinterpret_cast<const uint8_t*>(body_.data()) + it->second.first;
      *byte_size = it->second.second;
      return Error::Success();
    }
    auto jit = json_buffers_.find(output_name);
    if (jit != json_buffers_.end()) {
      *buf = reinterpret_cast<const uint8_t*>(jit->second.data());
      *byte_size = jit->second.size();
      return Error::Success();
    }
    return Error(
        "output '" + output_name + "' has no data in the response");
  }

  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override {
    const uint8_t* buf;
    size_t byte_size;
    Error err = RawData(output_name, &buf, &byte_size);
    if (err) return err;
    return DeserializeStrings(buf, byte_size, string_result);
  }

  Error IsFinalResponse(bool* is_final) const override {
    *is_final =
        header_.At("parameters").At("triton_final_response").AsBool();
    return Error::Success();
  }
  Error IsNullResponse(bool* is_null) const override {
    bool is_final = false;
    IsFinalResponse(&is_final);
    *is_null = is_final && header_.At("outputs").size() == 0;
    return Error::Success();
  }
  std::string DebugString() const override { return header_.Dump(); }
  Error RequestStatus() const override { return status_; }

 private:
  std::string body_;
  Json header_;
  Error status_;
  std::map<std::string, std::pair<size_t, size_t>> offsets_;
  std::map<std::string, std::string> json_buffers_;  // decoded JSON-mode data
};

}  // namespace

struct curl_slist* InferenceServerHttpClient::DefaultHeaderList(
    struct curl_slist* list) {
  std::lock_guard<std::mutex> lock(headers_mutex_);
  for (const auto& kv : default_headers_) {
    list = curl_slist_append(list, (kv.first + ": " + kv.second).c_str());
  }
  return list;
}

struct InferenceServerHttpClient::AsyncRequest {
  CURL* easy = nullptr;
  struct curl_slist* headers = nullptr;
  std::string body;
  std::string response;
  HeaderCapture capture;
  OnComplete callback;
  RequestTimers timers;
};

Error InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const std::string& server_url, bool verbose,
    const HttpSslOptions& ssl_options) {
  client->reset(
      new InferenceServerHttpClient(server_url, verbose, ssl_options));
  return Error::Success();
}

InferenceServerHttpClient::InferenceServerHttpClient(
    const std::string& url, bool verbose, const HttpSslOptions& ssl)
    : url_(url), verbose_(verbose), ssl_options_(ssl) {
  easy_ = curl_easy_init();
}

// Reference HttpSslOptions application (http_client.cc SetSSLCurlOptions):
// applied after every SetCommonOptions since curl_easy_reset clears state.
void InferenceServerHttpClient::ApplySslOptions(CURL* easy) {
  curl_easy_setopt(
      easy, CURLOPT_SSL_VERIFYPEER, ssl_options_.verify_peer ? 1L : 0L);
  curl_easy_setopt(
      easy, CURLOPT_SSL_VERIFYHOST, ssl_options_.verify_host ? 2L : 0L);
  if (!ssl_options_.ca_info.empty()) {
    curl_easy_setopt(easy, CURLOPT_CAINFO, ssl_options_.ca_info.c_str());
  }
  if (!ssl_options_.cert.empty()) {
    curl_easy_setopt(easy, CURLOPT_SSLCERT, ssl_options_.cert.c_str());
    curl_easy_setopt(easy, CURLOPT_SSLCERTTYPE, ssl_options_.cert_type.c_str());
  }
  if (!ssl_options_.key.empty()) {
    curl_easy_setopt(easy, CURLOPT_SSLKEY, ssl_options_.key.c_str());
    curl_easy_setopt(easy, CURLOPT_SSLKEYTYPE, ssl_options_.key_type.c_str());
  }
}

InferenceServerHttpClient::~InferenceServerHttpClient() {
  exiting_ = true;
  multi_cv_.notify_all();
  if (worker_.joinable()) worker_.join();
  if (multi_ != nullptr) curl_multi_cleanup(multi_);
  if (easy_ != nullptr) curl_easy_cleanup(easy_);
}

// ---------------------------------------------------------------------------
// transport
// ---------------------------------------------------------------------------

namespace {
void SetCommonOptions(
    CURL* easy, const std::string& url, const std::string* body,
    std::string* response, HeaderCapture* capture, uint64_t timeout_us) {
  curl_easy_setopt(easy, CURLOPT_URL, url.c_str());
  curl_easy_setopt(easy, CURLOPT_TCP_NODELAY, 1L);
  curl_easy_setopt(easy, CURLOPT_NOSIGNAL, 1L);
  // large tensor bodies: default 64KB/16KB transfer buffers throttle the
  // loopback path (reference uses 16MB both ways, http_client.cc:2099)
  curl_easy_setopt(easy, CURLOPT_UPLOAD_BUFFERSIZE, 16L * 1024 * 1024);
  curl_easy_setopt(easy, CURLOPT_BUFFERSIZE, 16L * 1024 * 1024);
  curl_easy_setopt(easy, CURLOPT_WRITEFUNCTION, WriteBody);
  curl_easy_setopt(easy, CURLOPT_WRITEDATA, response);
  curl_easy_setopt(easy, CURLOPT_HEADERFUNCTION, WriteHeader);
  curl_easy_setopt(easy, CURLOPT_HEADERDATA, capture);
  if (body != nullptr) {
    curl_easy_setopt(easy, CURLOPT_POST, 1L);
    curl_easy_setopt(easy, CURLOPT_POSTFIELDS, body->data());
    curl_easy_setopt(
        easy, CURLOPT_POSTFIELDSIZE_LARGE,
        static_cast<curl_off_t>(body->size()));
  } else {
    curl_easy_setopt(easy, CURLOPT_HTTPGET, 1L);
  }
  if (timeout_us != 0) {
    curl_easy_setopt(
        easy, CURLOPT_TIMEOUT_MS, static_cast<long>(timeout_us / 1000));
  }
}
}  // namespace

Error InferenceServerHttpClient::Perform(
    const std::string& path, const std::string* body, long* http_code,
    std::string* response) {
  std::lock_guard<std::mutex> lock(easy_mutex_);
  curl_easy_reset(easy_);
  HeaderCapture capture;
  SetCommonOptions(easy_, url_ + "/" + path, body, response, &capture, 0);
  ApplySslOptions(easy_);
  struct curl_slist* headers = DefaultHeaderList(nullptr);
  if (headers != nullptr) {
    curl_easy_setopt(easy_, CURLOPT_HTTPHEADER, headers);
  }
  CURLcode code = curl_easy_perform(easy_);
  curl_slist_free_all(headers);
  if (code != CURLE_OK) {
    return Error(std::string("HTTP request failed: ") + curl_easy_strerror(code));
  }
  curl_easy_getinfo(easy_, CURLINFO_RESPONSE_CODE, http_code);
  return Error::Success();
}

Error InferenceServerHttpClient::Get(
    const std::string& path, long* http_code, std::string* response) {
  return Perform(path, nullptr, http_code, response);
}

Error InferenceServerHttpClient::Post(
    const std::string& path, const std::string& body, long* http_code,
    std::string* response) {
  return Perform(path, &body, http_code, response);
}

Error InferenceServerHttpClient::GetJson(const std::string& path, Json* out) {
  long http_code = 0;
  std::string response;
  Error err = Get(path, &http_code, &response);
  if (err) return err;
  err = ErrorFromResponse(http_code, response);
  if (err) return err;
  if (response.empty()) {
    *out = Json::Object();
    return Error::Success();
  }
  std::string perr;
  if (!Json::Parse(response, out, &perr)) {
    return Error("failed to parse response: " + perr);
  }
  return Error::Success();
}

Error InferenceServerHttpClient::PostJson(
    const std::string& path, const std::string& body, Json* out) {
  long http_code = 0;
  std::string response;
  Error err = Post(path, body, &http_code, &response);
  if (err) return err;
  err = ErrorFromResponse(http_code, response);
  if (err) return err;
  if (out != nullptr && !response.empty()) {
    std::string perr;
    if (!Json::Parse(response, out, &perr)) {
      return Error("failed to parse response: " + perr);
    }
  } else if (out != nullptr) {
    *out = Json::Object();
  }
  return Error::Success();
}

// ---------------------------------------------------------------------------
// admin surface
// ---------------------------------------------------------------------------

Error InferenceServerHttpClient::IsServerLive(bool* live) {
  long http_code = 0;
  std::string response;
  Error err = Get("v2/health/live", &http_code, &response);
  *live = err.IsOk() && http_code == 200;
  return err;
}

Error InferenceServerHttpClient::IsServerReady(bool* ready) {
  long http_code = 0;
  std::string response;
  Error err = Get("v2/health/ready", &http_code, &response);
  *ready = err.IsOk() && http_code == 200;
  return err;
}

Error InferenceServerHttpClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version) {
  std::string path = "v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  long http_code = 0;
  std::string response;
  Error err = Get(path + "/ready", &http_code, &response);
  *ready = err.IsOk() && http_code == 200;
  return err;
}

Error InferenceServerHttpClient::ServerMetadata(Json* metadata) {
  return GetJson("v2", metadata);
}

Error InferenceServerHttpClient::ModelMetadata(
    Json* metadata, const std::string& model_name,
    const std::string& model_version) {
  std::string path = "v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  return GetJson(path, metadata);
}

Error InferenceServerHttpClient::ModelConfig(
    Json* config, const std::string& model_name,
    const std::string& model_version) {
  std::string path = "v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  return GetJson(path + "/config", config);
}

Error InferenceServerHttpClient::ModelRepositoryIndex(Json* index) {
  return PostJson("v2/repository/index", "", index);
}

Error InferenceServerHttpClient::LoadModel(
    const std::string& model_name, const std::string& config,
    const std::map<std::string, std::vector<char>>& files) {
  Json body = Json::Object();
  Json params = Json::Object();
  if (!config.empty()) params.Set("config", Json(config));
  for (const auto& kv : files) {
    params.Set(
        kv.first, Json(Base64Encode(
                      reinterpret_cast<const uint8_t*>(kv.second.data()),
                      kv.second.size())));
  }
  if (!params.items().empty()) body.Set("parameters", std::move(params));
  return PostJson(
      "v2/repository/models/" + model_name + "/load", body.Dump(), nullptr);
}

Error InferenceServerHttpClient::UnloadModel(const std::string& model_name) {
  return PostJson(
      "v2/repository/models/" + model_name + "/unload", "{}", nullptr);
}

Error InferenceServerHttpClient::ModelInferenceStatistics(
    Json* stats, const std::string& model_name,
    const std::string& model_version) {
  std::string path;
  if (!model_name.empty()) {
    path = "v2/models/" + model_name;
    if (!model_version.empty()) path += "/versions/" + model_version;
    path += "/stats";
  } else {
    path = "v2/models/stats";
  }
  return GetJson(path, stats);
}

Error InferenceServerHttpClient::UpdateTraceSettings(
    Json* response, const std::string& model_name, const Json& settings) {
  std::string path = model_name.empty()
                         ? "v2/trace/setting"
                         : "v2/models/" + model_name + "/trace/setting";
  return PostJson(path, settings.Dump(), response);
}

Error InferenceServerHttpClient::GetTraceSettings(
    Json* settings, const std::string& model_name) {
  std::string path = model_name.empty()
                         ? "v2/trace/setting"
                         : "v2/models/" + model_name + "/trace/setting";
  return GetJson(path, settings);
}

Error InferenceServerHttpClient::UpdateLogSettings(
    Json* response, const Json& settings) {
  return PostJson("v2/logging", settings.Dump(), response);
}

Error InferenceServerHttpClient::GetLogSettings(Json* settings) {
  return GetJson("v2/logging", settings);
}

Error InferenceServerHttpClient::ShmStatus(
    const std::string& family, const std::string& name, Json* out) {
  std::string path = "v2/" + family;
  if (!name.empty()) path += "/region/" + name;
  return GetJson(path + "/status", out);
}

Error InferenceServerHttpClient::ShmRegisterHandle(
    const std::string& family, const std::string& name,
    const std::string& raw_handle_b64, int device_id, size_t byte_size) {
  Json body = Json::Object();
  Json handle = Json::Object();
  handle.Set("b64", Json(raw_handle_b64));
  body.Set("raw_handle", std::move(handle));
  body.Set("device_id", Json(static_cast<int64_t>(device_id)));
  body.Set("byte_size", Json(static_cast<int64_t>(byte_size)));
  return PostJson(
      "v2/" + family + "/region/" + name + "/register", body.Dump(), nullptr);
}

Error InferenceServerHttpClient::ShmUnregister(
    const std::string& family, const std::string& name) {
  std::string path = "v2/" + family;
  if (!name.empty()) path += "/region/" + name;
  return PostJson(path + "/unregister", "", nullptr);
}

Error InferenceServerHttpClient::SystemSharedMemoryStatus(
    Json* status, const std::string& name) {
  return ShmStatus("systemsharedmemory", name, status);
}

Error InferenceServerHttpClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset) {
  Json body = Json::Object();
  body.Set("key", Json(key));
  body.Set("offset", Json(static_cast<int64_t>(offset)));
  body.Set("byte_size", Json(static_cast<int64_t>(byte_size)));
  return PostJson(
      "v2/systemsharedmemory/region/" + name + "/register", body.Dump(),
      nullptr);
}

Error InferenceServerHttpClient::UnregisterSystemSharedMemory(
    const std::string& name) {
  return ShmUnregister("systemsharedmemory", name);
}

Error InferenceServerHttpClient::TpuSharedMemoryStatus(
    Json* status, const std::string& name) {
  return ShmStatus("tpusharedmemory", name, status);
}

Error InferenceServerHttpClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle_b64, int device_id,
    size_t byte_size) {
  return ShmRegisterHandle(
      "tpusharedmemory", name, raw_handle_b64, device_id, byte_size);
}

Error InferenceServerHttpClient::UnregisterTpuSharedMemory(
    const std::string& name) {
  return ShmUnregister("tpusharedmemory", name);
}

Error InferenceServerHttpClient::CudaSharedMemoryStatus(
    Json* status, const std::string& name) {
  return ShmStatus("cudasharedmemory", name, status);
}

Error InferenceServerHttpClient::RegisterCudaSharedMemory(
    const std::string& name, const std::string& raw_handle_b64, int device_id,
    size_t byte_size) {
  return ShmRegisterHandle(
      "cudasharedmemory", name, raw_handle_b64, device_id, byte_size);
}

Error InferenceServerHttpClient::UnregisterCudaSharedMemory(
    const std::string& name) {
  return ShmUnregister("cudasharedmemory", name);
}

// ---------------------------------------------------------------------------
// inference
// ---------------------------------------------------------------------------

Error InferenceServerHttpClient::GenerateRequestBody(
    std::string* request_body, size_t* header_length,
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  *header_length = BuildInferBody(options, inputs, outputs, request_body);
  return Error::Success();
}

Error InferenceServerHttpClient::ParseResponseBody(
    InferResult** result, std::string&& response_body, size_t header_length) {
  // reference convention (http_client.h:121-137): 0 means the whole body is
  // the JSON header (no binary tail)
  long length = header_length == 0 ? -1 : static_cast<long>(header_length);
  return InferResultHttp::Create(result, std::move(response_body), length, 200);
}

Error InferenceServerHttpClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  RequestTimers timers;
  timers.Capture(RequestTimers::Kind::REQUEST_START);

  std::string body;
  size_t header_length = BuildInferBody(options, inputs, outputs, &body);
  std::string uri = url_ + "/v2/models/" + options.model_name;
  if (!options.model_version.empty()) {
    uri += "/versions/" + options.model_version;
  }
  uri += "/infer";

  std::string response;
  HeaderCapture capture;
  long http_code = 0;
  {
    std::lock_guard<std::mutex> lock(easy_mutex_);
    curl_easy_reset(easy_);
    SetCommonOptions(
        easy_, uri, &body, &response, &capture, options.client_timeout_us);
    ApplySslOptions(easy_);
    struct curl_slist* headers = DefaultHeaderList(nullptr);
    std::string hlen =
        "Inference-Header-Content-Length: " + std::to_string(header_length);
    headers = curl_slist_append(headers, hlen.c_str());
    headers =
        curl_slist_append(headers, "Content-Type: application/octet-stream");
    headers = curl_slist_append(headers, "Expect:");
    curl_easy_setopt(easy_, CURLOPT_HTTPHEADER, headers);

    timers.Capture(RequestTimers::Kind::SEND_START);
    CURLcode code = curl_easy_perform(easy_);
    timers.Capture(RequestTimers::Kind::SEND_END);
    curl_slist_free_all(headers);
    if (code == CURLE_OPERATION_TIMEDOUT) {
      return Error("Deadline Exceeded");
    }
    if (code != CURLE_OK) {
      return Error(
          std::string("HTTP request failed: ") + curl_easy_strerror(code));
    }
    curl_easy_getinfo(easy_, CURLINFO_RESPONSE_CODE, &http_code);
  }

  timers.Capture(RequestTimers::Kind::RECV_START);
  Error err = InferResultHttp::Create(
      result, std::move(response), capture.header_length, http_code);
  timers.Capture(RequestTimers::Kind::RECV_END);
  timers.Capture(RequestTimers::Kind::REQUEST_END);
  {
    std::lock_guard<std::mutex> lock(stat_mutex_);
    infer_stat_.Update(timers);
  }
  if (err) return err;
  return (*result)->RequestStatus();
}

Error InferenceServerHttpClient::AsyncInfer(
    OnComplete callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  {
    // guarded lazy start: two first-AsyncInfer threads must not both init
    std::lock_guard<std::mutex> lock(multi_mutex_);
    if (multi_ == nullptr) {
      multi_ = curl_multi_init();
      worker_ = std::thread(&InferenceServerHttpClient::AsyncTransfer, this);
    }
  }

  auto* request = new AsyncRequest();
  request->callback = std::move(callback);
  request->timers.Capture(RequestTimers::Kind::REQUEST_START);
  size_t header_length =
      BuildInferBody(options, inputs, outputs, &request->body);

  std::string uri = url_ + "/v2/models/" + options.model_name;
  if (!options.model_version.empty()) {
    uri += "/versions/" + options.model_version;
  }
  uri += "/infer";

  request->easy = curl_easy_init();
  SetCommonOptions(
      request->easy, uri, &request->body, &request->response,
      &request->capture, options.client_timeout_us);
  ApplySslOptions(request->easy);
  std::string hlen =
      "Inference-Header-Content-Length: " + std::to_string(header_length);
  request->headers = DefaultHeaderList(nullptr);
  request->headers = curl_slist_append(request->headers, hlen.c_str());
  request->headers = curl_slist_append(
      request->headers, "Content-Type: application/octet-stream");
  request->headers = curl_slist_append(request->headers, "Expect:");
  curl_easy_setopt(request->easy, CURLOPT_HTTPHEADER, request->headers);
  curl_easy_setopt(request->easy, CURLOPT_PRIVATE, request);

  {
    std::lock_guard<std::mutex> lock(multi_mutex_);
    pending_.push_back(request);
  }
  multi_cv_.notify_one();
  return Error::Success();
}

void InferenceServerHttpClient::AsyncTransfer() {
  int in_flight = 0;
  while (true) {
    {
      std::unique_lock<std::mutex> lock(multi_mutex_);
      if (pending_.empty() && in_flight == 0) {
        // drain fully before exiting: queued-but-unadded requests must
        // still run their callbacks
        if (exiting_) break;
        multi_cv_.wait_for(lock, std::chrono::milliseconds(100));
        if (exiting_ && pending_.empty()) break;
      }
      while (!pending_.empty()) {
        AsyncRequest* request = pending_.front();
        pending_.pop_front();
        request->timers.Capture(RequestTimers::Kind::SEND_START);
        curl_multi_add_handle(multi_, request->easy);
        ++in_flight;
      }
    }
    int running = 0;
    curl_multi_perform(multi_, &running);
    int msgs = 0;
    while (CURLMsg* msg = curl_multi_info_read(multi_, &msgs)) {
      if (msg->msg != CURLMSG_DONE) continue;
      AsyncRequest* request = nullptr;
      curl_easy_getinfo(
          msg->easy_handle, CURLINFO_PRIVATE,
          reinterpret_cast<char**>(&request));
      long http_code = 0;
      curl_easy_getinfo(msg->easy_handle, CURLINFO_RESPONSE_CODE, &http_code);
      request->timers.Capture(RequestTimers::Kind::SEND_END);
      request->timers.Capture(RequestTimers::Kind::RECV_START);
      InferResult* result = nullptr;
      if (msg->data.result == CURLE_OPERATION_TIMEDOUT) {
        http_code = 499;
        request->response = "{\"error\":\"Deadline Exceeded\"}";
      } else if (msg->data.result != CURLE_OK) {
        request->response = std::string("{\"error\":\"") +
                            curl_easy_strerror(msg->data.result) + "\"}";
        http_code = http_code >= 400 ? http_code : 500;
      }
      InferResultHttp::Create(
          &result, std::move(request->response),
          request->capture.header_length, http_code);
      request->timers.Capture(RequestTimers::Kind::RECV_END);
      request->timers.Capture(RequestTimers::Kind::REQUEST_END);
      {
        std::lock_guard<std::mutex> lock(stat_mutex_);
        infer_stat_.Update(request->timers);
      }
      curl_multi_remove_handle(multi_, msg->easy_handle);
      curl_easy_cleanup(request->easy);
      curl_slist_free_all(request->headers);
      request->callback(result);
      delete request;
      --in_flight;
    }
    if (running > 0) {
      curl_multi_wait(multi_, nullptr, 0, 50, nullptr);
    }
  }
}

namespace {
Error ValidateMultiSizes(
    size_t request_count, size_t options_count, size_t outputs_count) {
  if (request_count == 0) return Error("empty request list");
  if (options_count != 1 && options_count != request_count) {
    return Error(
        "options size must be 1 (broadcast) or match the request count");
  }
  if (outputs_count > 1 && outputs_count != request_count) {
    return Error(
        "outputs size must be 0, 1 (broadcast), or match the request count");
  }
  return Error::Success();
}
}  // namespace

Error InferenceServerHttpClient::InferMulti(
    std::vector<InferResult*>* results,
    const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs) {
  Error err = ValidateMultiSizes(inputs.size(), options.size(), outputs.size());
  if (err) return err;
  results->clear();
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs =
        outputs.empty() ? kNoOutputs
                        : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    InferResult* result = nullptr;
    err = Infer(&result, opt, inputs[i], outs);
    results->push_back(result);
    if (err) return err;
  }
  return Error::Success();
}

Error InferenceServerHttpClient::AsyncInferMulti(
    OnMultiComplete callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs) {
  Error err = ValidateMultiSizes(inputs.size(), options.size(), outputs.size());
  if (err) return err;
  // fan out every request; fire the callback once all land (reference's
  // atomic response counter, grpc_client.cc:1254-1320)
  struct MultiState {
    std::mutex mu;
    std::vector<InferResult*> results;
    size_t remaining;
    OnMultiComplete callback;
  };
  auto state = std::make_shared<MultiState>();
  state->results.resize(inputs.size(), nullptr);
  state->remaining = inputs.size();
  state->callback = std::move(callback);
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs =
        outputs.empty() ? kNoOutputs
                        : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    err = AsyncInfer(
        [state, i](InferResult* result) {
          bool done = false;
          {
            std::lock_guard<std::mutex> lock(state->mu);
            state->results[i] = result;
            done = (--state->remaining == 0);
          }
          if (done) state->callback(state->results);
        },
        opt, inputs[i], outs);
    if (err) return err;
  }
  return Error::Success();
}

InferStat InferenceServerHttpClient::ClientInferStat() {
  std::lock_guard<std::mutex> lock(stat_mutex_);
  return infer_stat_;
}

}  // namespace client_tpu
