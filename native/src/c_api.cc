// Flat C API over the native client for ctypes/cffi binding (this image has
// no pybind11; see client_tpu/native.py for the Python side).
#include <cstring>
#include <memory>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/http_client.h"
#include "client_tpu/tpu_shm.h"

using client_tpu::Error;
using client_tpu::InferenceServerHttpClient;
using client_tpu::InferInput;
using client_tpu::InferOptions;
using client_tpu::InferRequestedOutput;
using client_tpu::InferResult;
using client_tpu::Json;
using client_tpu::TpuShmRegion;

namespace {
thread_local std::string g_last_error;

int SetError(const Error& err) {
  if (err.IsOk()) return 0;
  g_last_error = err.Message();
  return -1;
}
}  // namespace

extern "C" {

const char* ctpu_last_error() { return g_last_error.c_str(); }

// -- client -----------------------------------------------------------------

void* ctpu_client_create(const char* url, int verbose) {
  std::unique_ptr<InferenceServerHttpClient> client;
  Error err = InferenceServerHttpClient::Create(&client, url, verbose != 0);
  if (SetError(err) != 0) return nullptr;
  return client.release();
}

void ctpu_client_destroy(void* client) {
  delete static_cast<InferenceServerHttpClient*>(client);
}

int ctpu_server_live(void* client) {
  bool live = false;
  Error err =
      static_cast<InferenceServerHttpClient*>(client)->IsServerLive(&live);
  if (SetError(err) != 0) return -1;
  return live ? 1 : 0;
}

int ctpu_model_ready(void* client, const char* model_name) {
  bool ready = false;
  Error err = static_cast<InferenceServerHttpClient*>(client)->IsModelReady(
      &ready, model_name);
  if (SetError(err) != 0) return -1;
  return ready ? 1 : 0;
}

// Single-input single-buffer inference helper: sends `input` and copies the
// named output back into `output` (up to output_capacity bytes). Returns the
// output byte size, or -1.
long long ctpu_infer_raw(
    void* client_ptr, const char* model_name, const char* input_name,
    const char* datatype, const long long* shape, int ndim,
    const void* input, unsigned long long input_byte_size,
    const char* output_name, void* output,
    unsigned long long output_capacity) {
  auto* client = static_cast<InferenceServerHttpClient*>(client_ptr);
  std::vector<int64_t> dims(shape, shape + ndim);
  InferInput* infer_input = nullptr;
  InferInput::Create(&infer_input, input_name, dims, datatype);
  std::unique_ptr<InferInput> input_guard(infer_input);
  infer_input->AppendRaw(
      static_cast<const uint8_t*>(input), input_byte_size);

  InferOptions options(model_name);
  InferResult* result = nullptr;
  Error err = client->Infer(&result, options, {infer_input});
  std::unique_ptr<InferResult> result_guard(result);
  if (SetError(err) != 0) return -1;

  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  err = result->RawData(output_name, &buf, &byte_size);
  if (SetError(err) != 0) return -1;
  if (byte_size > output_capacity) {
    g_last_error = "output buffer too small";
    return -1;
  }
  std::memcpy(output, buf, byte_size);
  return static_cast<long long>(byte_size);
}

int ctpu_register_system_shm(
    void* client, const char* name, const char* key,
    unsigned long long byte_size, unsigned long long offset) {
  return SetError(
      static_cast<InferenceServerHttpClient*>(client)
          ->RegisterSystemSharedMemory(name, key, byte_size, offset));
}

int ctpu_register_tpu_shm(
    void* client, const char* name, const char* raw_handle_b64, int device_id,
    unsigned long long byte_size) {
  return SetError(
      static_cast<InferenceServerHttpClient*>(client)->RegisterTpuSharedMemory(
          name, raw_handle_b64, device_id, byte_size));
}

int ctpu_unregister_shm(void* client, const char* family, const char* name) {
  auto* c = static_cast<InferenceServerHttpClient*>(client);
  std::string fam(family);
  if (fam == "system") return SetError(c->UnregisterSystemSharedMemory(name));
  if (fam == "tpu") return SetError(c->UnregisterTpuSharedMemory(name));
  if (fam == "cuda") return SetError(c->UnregisterCudaSharedMemory(name));
  g_last_error = "unknown shared-memory family";
  return -1;
}

// -- tpu shm regions ---------------------------------------------------------

void* ctpu_shm_create(const char* name, unsigned long long byte_size, int device_id) {
  TpuShmRegion* region = nullptr;
  Error err = TpuShmRegion::Create(&region, name, byte_size, device_id);
  if (SetError(err) != 0) return nullptr;
  return region;
}

void* ctpu_shm_attach(const char* raw_handle) {
  TpuShmRegion* region = nullptr;
  Error err = TpuShmRegion::Attach(&region, raw_handle);
  if (SetError(err) != 0) return nullptr;
  return region;
}

void ctpu_shm_destroy(void* region) {
  delete static_cast<TpuShmRegion*>(region);
}

const char* ctpu_shm_raw_handle(void* region) {
  thread_local std::string handle;
  handle = static_cast<TpuShmRegion*>(region)->RawHandle();
  return handle.c_str();
}

void* ctpu_shm_data(void* region) {
  return static_cast<TpuShmRegion*>(region)->Data();
}

int ctpu_shm_write(
    void* region, const void* src, unsigned long long byte_size,
    unsigned long long offset) {
  return SetError(
      static_cast<TpuShmRegion*>(region)->Write(src, byte_size, offset));
}

int ctpu_shm_read(
    void* region, void* dst, unsigned long long byte_size,
    unsigned long long offset) {
  return SetError(
      static_cast<TpuShmRegion*>(region)->Read(dst, byte_size, offset));
}

}  // extern "C"
