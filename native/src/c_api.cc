// Flat C API over the native client for ctypes/cffi binding (this image has
// no pybind11; see client_tpu/native.py for the Python side).
#include <cstring>
#include <memory>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"
#include "client_tpu/http_client.h"
#include "client_tpu/tpu_shm.h"

using client_tpu::Error;
using client_tpu::HttpSslOptions;
using client_tpu::InferenceServerGrpcClient;
using client_tpu::InferenceServerHttpClient;
using client_tpu::InferInput;
using client_tpu::InferOptions;
using client_tpu::InferRequestedOutput;
using client_tpu::InferResult;
using client_tpu::Json;
using client_tpu::TpuShmRegion;

namespace {
thread_local std::string g_last_error;

int SetError(const Error& err) {
  if (err.IsOk()) return 0;
  g_last_error = err.Message();
  return -1;
}
}  // namespace

extern "C" {

const char* ctpu_last_error() { return g_last_error.c_str(); }

// -- client -----------------------------------------------------------------

void* ctpu_client_create(const char* url, int verbose) {
  std::unique_ptr<InferenceServerHttpClient> client;
  Error err = InferenceServerHttpClient::Create(&client, url, verbose != 0);
  if (SetError(err) != 0) return nullptr;
  return client.release();
}

// HTTPS variant: ca/cert/key are file paths (empty/NULL = unset).
void* ctpu_client_create_ssl(
    const char* url, int verbose, const char* ca_cert, const char* client_cert,
    const char* client_key, int verify_peer, int verify_host) {
  HttpSslOptions ssl;
  ssl.verify_peer = verify_peer != 0;
  ssl.verify_host = verify_host != 0;
  if (ca_cert != nullptr) ssl.ca_info = ca_cert;
  if (client_cert != nullptr) ssl.cert = client_cert;
  if (client_key != nullptr) ssl.key = client_key;
  std::unique_ptr<InferenceServerHttpClient> client;
  Error err =
      InferenceServerHttpClient::Create(&client, url, verbose != 0, ssl);
  if (SetError(err) != 0) return nullptr;
  return client.release();
}

void ctpu_client_destroy(void* client) {
  delete static_cast<InferenceServerHttpClient*>(client);
}

int ctpu_server_live(void* client) {
  bool live = false;
  Error err =
      static_cast<InferenceServerHttpClient*>(client)->IsServerLive(&live);
  if (SetError(err) != 0) return -1;
  return live ? 1 : 0;
}

int ctpu_model_ready(void* client, const char* model_name) {
  bool ready = false;
  Error err = static_cast<InferenceServerHttpClient*>(client)->IsModelReady(
      &ready, model_name);
  if (SetError(err) != 0) return -1;
  return ready ? 1 : 0;
}

// Single-input single-buffer inference helper: sends `input` and copies the
// named output back into `output` (up to output_capacity bytes). Returns the
// output byte size, or -1.
long long ctpu_infer_raw(
    void* client_ptr, const char* model_name, const char* input_name,
    const char* datatype, const long long* shape, int ndim,
    const void* input, unsigned long long input_byte_size,
    const char* output_name, void* output,
    unsigned long long output_capacity) {
  auto* client = static_cast<InferenceServerHttpClient*>(client_ptr);
  std::vector<int64_t> dims(shape, shape + ndim);
  InferInput* infer_input = nullptr;
  InferInput::Create(&infer_input, input_name, dims, datatype);
  std::unique_ptr<InferInput> input_guard(infer_input);
  infer_input->AppendRaw(
      static_cast<const uint8_t*>(input), input_byte_size);

  InferOptions options(model_name);
  InferResult* result = nullptr;
  Error err = client->Infer(&result, options, {infer_input});
  std::unique_ptr<InferResult> result_guard(result);
  if (SetError(err) != 0) return -1;

  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  err = result->RawData(output_name, &buf, &byte_size);
  if (SetError(err) != 0) return -1;
  if (byte_size > output_capacity) {
    g_last_error = "output buffer too small";
    return -1;
  }
  std::memcpy(output, buf, byte_size);
  return static_cast<long long>(byte_size);
}

int ctpu_register_system_shm(
    void* client, const char* name, const char* key,
    unsigned long long byte_size, unsigned long long offset) {
  return SetError(
      static_cast<InferenceServerHttpClient*>(client)
          ->RegisterSystemSharedMemory(name, key, byte_size, offset));
}

int ctpu_register_tpu_shm(
    void* client, const char* name, const char* raw_handle_b64, int device_id,
    unsigned long long byte_size) {
  return SetError(
      static_cast<InferenceServerHttpClient*>(client)->RegisterTpuSharedMemory(
          name, raw_handle_b64, device_id, byte_size));
}

int ctpu_unregister_shm(void* client, const char* family, const char* name) {
  auto* c = static_cast<InferenceServerHttpClient*>(client);
  std::string fam(family);
  if (fam == "system") return SetError(c->UnregisterSystemSharedMemory(name));
  if (fam == "tpu") return SetError(c->UnregisterTpuSharedMemory(name));
  if (fam == "cuda") return SetError(c->UnregisterCudaSharedMemory(name));
  g_last_error = "unknown shared-memory family";
  return -1;
}

// -- full value-model surface -------------------------------------------------
// Handle-based API so any FFI language drives multi-input inference with
// options, shared-memory placement, and result introspection.

void* ctpu_input_create(
    const char* name, const char* datatype, const long long* shape, int ndim) {
  std::vector<int64_t> dims(shape, shape + ndim);
  InferInput* input = nullptr;
  Error err = InferInput::Create(&input, name, dims, datatype);
  if (SetError(err) != 0) return nullptr;
  return input;
}

void ctpu_input_destroy(void* input) { delete static_cast<InferInput*>(input); }

// NOTE: no copy — `data` must stay valid until the infer call returns.
int ctpu_input_append_raw(
    void* input, const void* data, unsigned long long byte_size) {
  return SetError(static_cast<InferInput*>(input)->AppendRaw(
      static_cast<const uint8_t*>(data), byte_size));
}

int ctpu_input_set_shm(
    void* input, const char* region, unsigned long long byte_size,
    unsigned long long offset) {
  return SetError(static_cast<InferInput*>(input)->SetSharedMemory(
      region, byte_size, offset));
}

int ctpu_input_reset(void* input) {
  return SetError(static_cast<InferInput*>(input)->Reset());
}

void* ctpu_output_create(const char* name, unsigned long long class_count) {
  InferRequestedOutput* output = nullptr;
  Error err = InferRequestedOutput::Create(&output, name, class_count);
  if (SetError(err) != 0) return nullptr;
  return output;
}

void ctpu_output_destroy(void* output) {
  delete static_cast<InferRequestedOutput*>(output);
}

int ctpu_output_set_shm(
    void* output, const char* region, unsigned long long byte_size,
    unsigned long long offset) {
  return SetError(static_cast<InferRequestedOutput*>(output)->SetSharedMemory(
      region, byte_size, offset));
}

void* ctpu_options_create(const char* model_name) {
  return new InferOptions(model_name);
}

void ctpu_options_destroy(void* options) {
  delete static_cast<InferOptions*>(options);
}

void ctpu_options_set_request_id(void* options, const char* request_id) {
  static_cast<InferOptions*>(options)->request_id = request_id;
}

void ctpu_options_set_sequence(
    void* options, unsigned long long sequence_id, int sequence_start,
    int sequence_end) {
  auto* o = static_cast<InferOptions*>(options);
  o->sequence_id = sequence_id;
  o->sequence_start = sequence_start != 0;
  o->sequence_end = sequence_end != 0;
}

void ctpu_options_set_timeouts(
    void* options, unsigned long long client_timeout_us,
    unsigned long long server_timeout_us) {
  auto* o = static_cast<InferOptions*>(options);
  o->client_timeout_us = client_timeout_us;
  o->server_timeout_us = server_timeout_us;
}

int ctpu_infer(
    void* client, void* options, void** inputs, int n_inputs, void** outputs,
    int n_outputs, void** result_out) {
  std::vector<InferInput*> ins(n_inputs);
  for (int i = 0; i < n_inputs; ++i) ins[i] = static_cast<InferInput*>(inputs[i]);
  std::vector<const InferRequestedOutput*> outs(n_outputs);
  for (int i = 0; i < n_outputs; ++i) {
    outs[i] = static_cast<const InferRequestedOutput*>(outputs[i]);
  }
  InferResult* result = nullptr;
  Error err = static_cast<InferenceServerHttpClient*>(client)->Infer(
      &result, *static_cast<InferOptions*>(options), ins, outs);
  *result_out = result;
  return SetError(err);
}

// -- result accessors --------------------------------------------------------

void ctpu_result_destroy(void* result) {
  delete static_cast<InferResult*>(result);
}

// Zero-copy view into the result's buffer; valid while the result lives.
int ctpu_result_raw(
    void* result, const char* output_name, const void** buf,
    unsigned long long* byte_size) {
  const uint8_t* data = nullptr;
  size_t size = 0;
  Error err = static_cast<InferResult*>(result)->RawData(output_name, &data, &size);
  *buf = data;
  *byte_size = size;
  return SetError(err);
}

// Fills `dims` (capacity `max_ndim`); returns ndim or -1.
int ctpu_result_shape(
    void* result, const char* output_name, long long* dims, int max_ndim) {
  std::vector<int64_t> shape;
  Error err = static_cast<InferResult*>(result)->Shape(output_name, &shape);
  if (SetError(err) != 0) return -1;
  if (static_cast<int>(shape.size()) > max_ndim) {
    g_last_error = "shape buffer too small";
    return -1;
  }
  for (size_t i = 0; i < shape.size(); ++i) dims[i] = shape[i];
  return static_cast<int>(shape.size());
}

const char* ctpu_result_datatype(void* result, const char* output_name) {
  thread_local std::string datatype;
  Error err =
      static_cast<InferResult*>(result)->Datatype(output_name, &datatype);
  if (SetError(err) != 0) return nullptr;
  return datatype.c_str();
}

// All output names, newline-joined (one call for O(n) enumeration).
const char* ctpu_result_output_names(void* result) {
  thread_local std::string joined;
  std::vector<std::string> names;
  static_cast<InferResult*>(result)->OutputNames(&names);
  joined.clear();
  for (size_t i = 0; i < names.size(); ++i) {
    if (i) joined.push_back('\n');
    joined += names[i];
  }
  return joined.c_str();
}

// Returns the index-th output name, or NULL past the end.
const char* ctpu_result_output_name(void* result, int index) {
  thread_local std::string name;
  std::vector<std::string> names;
  static_cast<InferResult*>(result)->OutputNames(&names);
  if (index < 0 || static_cast<size_t>(index) >= names.size()) return nullptr;
  name = names[index];
  return name.c_str();
}

const char* ctpu_result_model_name(void* result) {
  thread_local std::string name;
  static_cast<InferResult*>(result)->ModelName(&name);
  return name.c_str();
}

// NULL when the request succeeded; the error message otherwise. Async
// completions deliver failures as a result whose RequestStatus carries the
// error (the reference callback contract), so callbacks need this to tell
// the two apart.
const char* ctpu_result_status(void* result) {
  thread_local std::string message;
  Error err = static_cast<InferResult*>(result)->RequestStatus();
  if (!err) return nullptr;
  message = err.Message();
  return message.c_str();
}

// -- async ---------------------------------------------------------------------

typedef void (*ctpu_callback)(void* user, void* result);

int ctpu_async_infer(
    void* client, void* options, void** inputs, int n_inputs, void** outputs,
    int n_outputs, ctpu_callback callback, void* user) {
  std::vector<InferInput*> ins(n_inputs);
  for (int i = 0; i < n_inputs; ++i) ins[i] = static_cast<InferInput*>(inputs[i]);
  std::vector<const InferRequestedOutput*> outs(n_outputs);
  for (int i = 0; i < n_outputs; ++i) {
    outs[i] = static_cast<const InferRequestedOutput*>(outputs[i]);
  }
  Error err = static_cast<InferenceServerHttpClient*>(client)->AsyncInfer(
      [callback, user](InferResult* result) { callback(user, result); },
      *static_cast<InferOptions*>(options), ins, outs);
  return SetError(err);
}

int ctpu_set_header(void* client, const char* key, const char* value) {
  static_cast<InferenceServerHttpClient*>(client)->AddDefaultHeader(key, value);
  return 0;
}

// -- grpc client --------------------------------------------------------------
// Same handle/value-model surface over InferenceServerGrpcClient; results
// flow back through the shared ctpu_result_* accessors (InferResult is
// polymorphic across both clients).

void* ctpu_grpc_client_create(const char* url, int verbose) {
  std::unique_ptr<InferenceServerGrpcClient> client;
  Error err = InferenceServerGrpcClient::Create(&client, url, verbose != 0);
  if (SetError(err) != 0) return nullptr;
  return client.release();
}

// TLS variant (grpc-over-TLS on the library's own h2 via the system libssl
// runtime). ca/cert/key are PEM file paths.
void* ctpu_grpc_client_create_ssl(
    const char* url, int verbose, const char* ca_cert, const char* client_cert,
    const char* client_key, int verify_peer, int verify_host) {
  client_tpu::tls::TlsOptions ssl;
  ssl.use_tls = true;
  ssl.verify_peer = verify_peer != 0;
  ssl.verify_host = verify_host != 0;
  if (ca_cert != nullptr) ssl.ca_cert_file = ca_cert;
  if (client_cert != nullptr) ssl.client_cert_file = client_cert;
  if (client_key != nullptr) ssl.client_key_file = client_key;
  std::unique_ptr<InferenceServerGrpcClient> client;
  Error err =
      InferenceServerGrpcClient::Create(&client, url, verbose != 0, ssl);
  if (SetError(err) != 0) return nullptr;
  return client.release();
}

void ctpu_grpc_client_destroy(void* client) {
  delete static_cast<InferenceServerGrpcClient*>(client);
}

int ctpu_grpc_server_live(void* client) {
  bool live = false;
  Error err =
      static_cast<InferenceServerGrpcClient*>(client)->IsServerLive(&live);
  if (SetError(err) != 0) return -1;
  return live ? 1 : 0;
}

int ctpu_grpc_model_ready(void* client, const char* model_name) {
  bool ready = false;
  Error err = static_cast<InferenceServerGrpcClient*>(client)->IsModelReady(
      &ready, model_name);
  if (SetError(err) != 0) return -1;
  return ready ? 1 : 0;
}

int ctpu_grpc_infer(
    void* client, void* options, void** inputs, int n_inputs, void** outputs,
    int n_outputs, void** result_out) {
  std::vector<InferInput*> ins(n_inputs);
  for (int i = 0; i < n_inputs; ++i) ins[i] = static_cast<InferInput*>(inputs[i]);
  std::vector<const InferRequestedOutput*> outs(n_outputs);
  for (int i = 0; i < n_outputs; ++i) {
    outs[i] = static_cast<const InferRequestedOutput*>(outputs[i]);
  }
  InferResult* result = nullptr;
  Error err = static_cast<InferenceServerGrpcClient*>(client)->Infer(
      &result, *static_cast<InferOptions*>(options), ins, outs);
  *result_out = result;
  return SetError(err);
}

int ctpu_grpc_async_infer(
    void* client, void* options, void** inputs, int n_inputs, void** outputs,
    int n_outputs, ctpu_callback callback, void* user) {
  std::vector<InferInput*> ins(n_inputs);
  for (int i = 0; i < n_inputs; ++i) ins[i] = static_cast<InferInput*>(inputs[i]);
  std::vector<const InferRequestedOutput*> outs(n_outputs);
  for (int i = 0; i < n_outputs; ++i) {
    outs[i] = static_cast<const InferRequestedOutput*>(outputs[i]);
  }
  Error err = static_cast<InferenceServerGrpcClient*>(client)->AsyncInfer(
      [callback, user](InferResult* result) { callback(user, result); },
      *static_cast<InferOptions*>(options), ins, outs);
  return SetError(err);
}

int ctpu_grpc_register_system_shm(
    void* client, const char* name, const char* key,
    unsigned long long byte_size, unsigned long long offset) {
  return SetError(
      static_cast<InferenceServerGrpcClient*>(client)
          ->RegisterSystemSharedMemory(name, key, byte_size, offset));
}

int ctpu_grpc_register_tpu_shm(
    void* client, const char* name, const char* raw_handle, int device_id,
    unsigned long long byte_size) {
  return SetError(
      static_cast<InferenceServerGrpcClient*>(client)->RegisterTpuSharedMemory(
          name, raw_handle, device_id, byte_size));
}

int ctpu_grpc_set_header(void* client, const char* key, const char* value) {
  static_cast<InferenceServerGrpcClient*>(client)->AddDefaultHeader(key, value);
  return 0;
}

// Default message compression: "gzip", "deflate", or "" (off).
int ctpu_grpc_set_compression(void* client, const char* algorithm) {
  static_cast<InferenceServerGrpcClient*>(client)->SetCompression(
      algorithm == nullptr ? "" : algorithm);
  return 0;
}

// In-flight window for the async completion-queue worker.
int ctpu_grpc_set_async_concurrency(void* client, int n) {
  static_cast<InferenceServerGrpcClient*>(client)->SetAsyncConcurrency(
      n < 1 ? 1 : static_cast<size_t>(n));
  return 0;
}

int ctpu_grpc_unregister_shm(
    void* client, const char* family, const char* name) {
  auto* c = static_cast<InferenceServerGrpcClient*>(client);
  std::string fam(family);
  if (fam == "system") return SetError(c->UnregisterSystemSharedMemory(name));
  if (fam == "tpu") return SetError(c->UnregisterTpuSharedMemory(name));
  if (fam == "cuda") return SetError(c->UnregisterCudaSharedMemory(name));
  g_last_error = "unknown shared-memory family";
  return -1;
}

// grpc bi-di streaming: callback receives (user, result, error_message);
// result may be null on stream errors and must be freed by the callee via
// ctpu_result_destroy when non-null. error_message is valid only for the
// duration of the call.
typedef void (*ctpu_stream_callback)(
    void* user, void* result, const char* error_message);

int ctpu_grpc_start_stream(
    void* client, ctpu_stream_callback callback, void* user) {
  return SetError(
      static_cast<InferenceServerGrpcClient*>(client)->StartStream(
          [callback, user](InferResult* result, const Error& err) {
            callback(user, result, err.IsOk() ? nullptr : err.Message().c_str());
          }));
}

int ctpu_grpc_stream_infer(
    void* client, void* options, void** inputs, int n_inputs, void** outputs,
    int n_outputs) {
  std::vector<InferInput*> ins(n_inputs);
  for (int i = 0; i < n_inputs; ++i) ins[i] = static_cast<InferInput*>(inputs[i]);
  std::vector<const InferRequestedOutput*> outs(n_outputs);
  for (int i = 0; i < n_outputs; ++i) {
    outs[i] = static_cast<const InferRequestedOutput*>(outputs[i]);
  }
  return SetError(
      static_cast<InferenceServerGrpcClient*>(client)->AsyncStreamInfer(
          *static_cast<InferOptions*>(options), ins, outs));
}

int ctpu_grpc_stop_stream(void* client) {
  return SetError(
      static_cast<InferenceServerGrpcClient*>(client)->StopStream());
}

// -- tpu shm regions ---------------------------------------------------------

void* ctpu_shm_create(const char* name, unsigned long long byte_size, int device_id) {
  TpuShmRegion* region = nullptr;
  Error err = TpuShmRegion::Create(&region, name, byte_size, device_id);
  if (SetError(err) != 0) return nullptr;
  return region;
}

void* ctpu_shm_attach(const char* raw_handle) {
  TpuShmRegion* region = nullptr;
  Error err = TpuShmRegion::Attach(&region, raw_handle);
  if (SetError(err) != 0) return nullptr;
  return region;
}

void ctpu_shm_destroy(void* region) {
  delete static_cast<TpuShmRegion*>(region);
}

const char* ctpu_shm_raw_handle(void* region) {
  thread_local std::string handle;
  handle = static_cast<TpuShmRegion*>(region)->RawHandle();
  return handle.c_str();
}

void* ctpu_shm_data(void* region) {
  return static_cast<TpuShmRegion*>(region)->Data();
}

int ctpu_shm_write(
    void* region, const void* src, unsigned long long byte_size,
    unsigned long long offset) {
  return SetError(
      static_cast<TpuShmRegion*>(region)->Write(src, byte_size, offset));
}

int ctpu_shm_read(
    void* region, void* dst, unsigned long long byte_size,
    unsigned long long offset) {
  return SetError(
      static_cast<TpuShmRegion*>(region)->Read(dst, byte_size, offset));
}

}  // extern "C"
