#include "client_tpu/common.h"

namespace client_tpu {

Error InferInput::Create(
    InferInput** result, const std::string& name,
    const std::vector<int64_t>& shape, const std::string& datatype) {
  *result = new InferInput(name, shape, datatype);
  return Error::Success();
}

Error InferInput::AppendRaw(const uint8_t* input, size_t input_byte_size) {
  if (InSharedMemory()) {
    return Error("cannot append raw data to an input placed in shared memory");
  }
  buffers_.emplace_back(input, input_byte_size);
  total_byte_size_ += input_byte_size;
  return Error::Success();
}

Error InferInput::AppendFromString(const std::vector<std::string>& input) {
  std::string serialized;
  SerializeStrings(input, &serialized);
  owned_.push_back(std::move(serialized));
  const std::string& stored = owned_.back();
  return AppendRaw(
      reinterpret_cast<const uint8_t*>(stored.data()), stored.size());
}

Error InferInput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset) {
  buffers_.clear();
  owned_.clear();
  total_byte_size_ = 0;
  shm_region_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success();
}

Error InferInput::Reset() {
  buffers_.clear();
  owned_.clear();
  total_byte_size_ = 0;
  shm_region_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success();
}

Error InferRequestedOutput::Create(
    InferRequestedOutput** result, const std::string& name,
    size_t class_count) {
  *result = new InferRequestedOutput(name, class_count);
  return Error::Success();
}

Error InferRequestedOutput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset) {
  shm_region_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success();
}

Error InferRequestedOutput::UnsetSharedMemory() {
  shm_region_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success();
}

void SerializeStrings(
    const std::vector<std::string>& input, std::string* output) {
  size_t total = 0;
  for (const auto& s : input) total += 4 + s.size();
  output->clear();
  output->reserve(total);
  for (const auto& s : input) {
    uint32_t len = static_cast<uint32_t>(s.size());
    output->append(reinterpret_cast<const char*>(&len), 4);  // little-endian
    output->append(s);
  }
}

Error DeserializeStrings(
    const uint8_t* buf, size_t byte_size, std::vector<std::string>* output) {
  size_t offset = 0;
  while (offset < byte_size) {
    if (offset + 4 > byte_size) {
      return Error("malformed BYTES tensor: truncated length prefix");
    }
    uint32_t len;
    std::memcpy(&len, buf + offset, 4);
    offset += 4;
    if (offset + len > byte_size) {
      return Error("malformed BYTES tensor: truncated element");
    }
    output->emplace_back(reinterpret_cast<const char*>(buf + offset), len);
    offset += len;
  }
  return Error::Success();
}

}  // namespace client_tpu
