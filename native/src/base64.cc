#include "client_tpu/base64.h"

namespace client_tpu {

namespace {
const char kAlphabet[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

int8_t DecodeChar(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}
}  // namespace

std::string Base64Encode(const uint8_t* data, size_t size) {
  std::string out;
  out.reserve(((size + 2) / 3) * 4);
  size_t i = 0;
  for (; i + 3 <= size; i += 3) {
    uint32_t n = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2];
    out.push_back(kAlphabet[(n >> 18) & 63]);
    out.push_back(kAlphabet[(n >> 12) & 63]);
    out.push_back(kAlphabet[(n >> 6) & 63]);
    out.push_back(kAlphabet[n & 63]);
  }
  if (i + 1 == size) {
    uint32_t n = data[i] << 16;
    out.push_back(kAlphabet[(n >> 18) & 63]);
    out.push_back(kAlphabet[(n >> 12) & 63]);
    out += "==";
  } else if (i + 2 == size) {
    uint32_t n = (data[i] << 16) | (data[i + 1] << 8);
    out.push_back(kAlphabet[(n >> 18) & 63]);
    out.push_back(kAlphabet[(n >> 12) & 63]);
    out.push_back(kAlphabet[(n >> 6) & 63]);
    out.push_back('=');
  }
  return out;
}

bool Base64Decode(const std::string& encoded, std::vector<uint8_t>* out) {
  out->clear();
  uint32_t acc = 0;
  int bits = 0;
  for (char c : encoded) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int8_t v = DecodeChar(c);
    if (v < 0) return false;
    acc = (acc << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back(static_cast<uint8_t>((acc >> bits) & 0xFF));
    }
  }
  return true;
}

}  // namespace client_tpu
