#include "client_tpu/json.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace client_tpu {

namespace {
const Json kNullJson;

struct Parser {
  const char* p;
  const char* end;
  std::string error;

  void SkipWs() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool Fail(const std::string& msg) {
    error = msg;
    return false;
  }

  bool ParseValue(Json* out) {
    SkipWs();
    if (p >= end) return Fail("unexpected end of input");
    switch (*p) {
      case '{':
        return ParseObject(out);
      case '[':
        return ParseArray(out);
      case '"': {
        std::string s;
        if (!ParseString(&s)) return false;
        *out = Json(s);
        return true;
      }
      case 't':
        if (end - p >= 4 && strncmp(p, "true", 4) == 0) {
          p += 4;
          *out = Json(true);
          return true;
        }
        return Fail("bad literal");
      case 'f':
        if (end - p >= 5 && strncmp(p, "false", 5) == 0) {
          p += 5;
          *out = Json(false);
          return true;
        }
        return Fail("bad literal");
      case 'n':
        if (end - p >= 4 && strncmp(p, "null", 4) == 0) {
          p += 4;
          *out = Json();
          return true;
        }
        return Fail("bad literal");
      default:
        return ParseNumber(out);
    }
  }

  bool ParseString(std::string* out) {
    if (*p != '"') return Fail("expected string");
    ++p;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) return Fail("bad escape");
        switch (*p) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            if (end - p < 5) return Fail("bad \\u escape");
            unsigned int code = 0;
            for (int i = 1; i <= 4; ++i) {
              char c = p[i];
              code <<= 4;
              if (c >= '0' && c <= '9') code |= c - '0';
              else if (c >= 'a' && c <= 'f') code |= c - 'a' + 10;
              else if (c >= 'A' && c <= 'F') code |= c - 'A' + 10;
              else return Fail("bad \\u escape");
            }
            p += 4;
            // UTF-8 encode the BMP code point (surrogate pairs unsupported)
            if (code < 0x80) {
              out->push_back(static_cast<char>(code));
            } else if (code < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (code >> 6)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xE0 | (code >> 12)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            break;
          }
          default:
            return Fail("bad escape");
        }
        ++p;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return Fail("unterminated string");
    ++p;  // closing quote
    return true;
  }

  bool ParseNumber(Json* out) {
    const char* start = p;
    if (p < end && (*p == '-' || *p == '+')) ++p;
    bool is_double = false;
    while (p < end && (isdigit(*p) || *p == '.' || *p == 'e' || *p == 'E' ||
                       *p == '-' || *p == '+')) {
      if (*p == '.' || *p == 'e' || *p == 'E') is_double = true;
      ++p;
    }
    if (p == start) return Fail("expected number");
    std::string num(start, p - start);
    if (is_double) {
      *out = Json(strtod(num.c_str(), nullptr));
    } else {
      *out = Json(static_cast<int64_t>(strtoll(num.c_str(), nullptr, 10)));
    }
    return true;
  }

  bool ParseObject(Json* out) {
    *out = Json::Object();
    ++p;  // '{'
    SkipWs();
    if (p < end && *p == '}') {
      ++p;
      return true;
    }
    while (p < end) {
      SkipWs();
      std::string key;
      if (!ParseString(&key)) return false;
      SkipWs();
      if (p >= end || *p != ':') return Fail("expected ':'");
      ++p;
      Json value;
      if (!ParseValue(&value)) return false;
      out->Set(key, std::move(value));
      SkipWs();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == '}') {
        ++p;
        return true;
      }
      return Fail("expected ',' or '}'");
    }
    return Fail("unterminated object");
  }

  bool ParseArray(Json* out) {
    *out = Json::Array();
    ++p;  // '['
    SkipWs();
    if (p < end && *p == ']') {
      ++p;
      return true;
    }
    while (p < end) {
      Json value;
      if (!ParseValue(&value)) return false;
      out->Append(std::move(value));
      SkipWs();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == ']') {
        ++p;
        return true;
      }
      return Fail("expected ',' or ']'");
    }
    return Fail("unterminated array");
  }
};

void DumpString(const std::string& s, std::string* out) {
  out->push_back('"');
  for (char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(c);
        }
    }
  }
  out->push_back('"');
}

void DumpValue(const Json& j, std::string* out) {
  switch (j.type()) {
    case Json::Type::kNull:
      *out += "null";
      break;
    case Json::Type::kBool:
      *out += j.AsBool() ? "true" : "false";
      break;
    case Json::Type::kInt: {
      char buf[32];
      snprintf(buf, sizeof(buf), "%lld", static_cast<long long>(j.AsInt()));
      *out += buf;
      break;
    }
    case Json::Type::kDouble: {
      char buf[64];
      snprintf(buf, sizeof(buf), "%.17g", j.AsDouble());
      *out += buf;
      break;
    }
    case Json::Type::kString:
      DumpString(j.AsString(), out);
      break;
    case Json::Type::kArray: {
      out->push_back('[');
      for (size_t i = 0; i < j.size(); ++i) {
        if (i) out->push_back(',');
        DumpValue(j[i], out);
      }
      out->push_back(']');
      break;
    }
    case Json::Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& kv : j.items()) {
        if (!first) out->push_back(',');
        first = false;
        DumpString(kv.first, out);
        out->push_back(':');
        DumpValue(kv.second, out);
      }
      out->push_back('}');
      break;
    }
  }
}

}  // namespace

const Json& Json::At(const std::string& key) const {
  auto it = object_.find(key);
  return it == object_.end() ? kNullJson : it->second;
}

std::string Json::Dump() const {
  std::string out;
  DumpValue(*this, &out);
  return out;
}

bool Json::Parse(const std::string& text, Json* out, std::string* error) {
  Parser parser{text.data(), text.data() + text.size()};
  if (!parser.ParseValue(out)) {
    if (error) *error = parser.error;
    return false;
  }
  return true;
}

}  // namespace client_tpu
