// HTTP/2 + HPACK client transport implementation. See h2.h for scope.

#include "client_tpu/h2.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>

namespace client_tpu {
namespace h2 {

namespace {

#include "hpack_tables.inc"

// frame types
constexpr uint8_t kData = 0x0;
constexpr uint8_t kHeaders = 0x1;
constexpr uint8_t kRstStream = 0x3;
constexpr uint8_t kSettings = 0x4;
constexpr uint8_t kPushPromise = 0x5;
constexpr uint8_t kPing = 0x6;
constexpr uint8_t kGoaway = 0x7;
constexpr uint8_t kWindowUpdate = 0x8;
constexpr uint8_t kContinuation = 0x9;

// flags
constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

// our receive windows: announce large windows once, replenish as consumed
constexpr int64_t kRecvWindow = 1 << 28;  // 256 MiB

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// huffman decoding: bit-walk a tree built once from the RFC code table
// ---------------------------------------------------------------------------

struct HuffNode {
  int16_t child[2] = {-1, -1};
  int16_t symbol = -1;  // 0..255 terminal, 256 EOS
};

struct HuffTree {
  std::vector<HuffNode> nodes;
  HuffTree() {
    nodes.emplace_back();
    for (int sym = 0; sym < 257; ++sym) {
      uint32_t code = kHuffmanCodes[sym].code;
      int bits = kHuffmanCodes[sym].bits;
      int at = 0;
      for (int b = bits - 1; b >= 0; --b) {
        int bit = (code >> b) & 1;
        if (nodes[at].child[bit] < 0) {
          nodes[at].child[bit] = static_cast<int16_t>(nodes.size());
          nodes.emplace_back();
        }
        at = nodes[at].child[bit];
      }
      nodes[at].symbol = static_cast<int16_t>(sym);
    }
  }
};
const HuffTree& Tree() {
  static HuffTree tree;
  return tree;
}

Error HuffmanDecode(const uint8_t* data, size_t size, std::string* out) {
  const HuffTree& tree = Tree();
  int at = 0;
  int pending_bits = 0;  // bits consumed since the last completed symbol
  int ones_run = 0;      // consecutive 1-bits ending at the current bit
  for (size_t i = 0; i < size; ++i) {
    for (int b = 7; b >= 0; --b) {
      int bit = (data[i] >> b) & 1;
      ones_run = bit ? ones_run + 1 : 0;
      ++pending_bits;
      at = tree.nodes[at].child[bit];
      if (at < 0) return Error("hpack: invalid huffman sequence");
      int16_t sym = tree.nodes[at].symbol;
      if (sym >= 0) {
        if (sym == 256) return Error("hpack: unexpected EOS symbol");
        out->push_back(static_cast<char>(sym));
        at = 0;
        pending_bits = 0;
        ones_run = 0;
      }
    }
  }
  // trailing bits must be the all-ones EOS prefix, shorter than 8 bits
  if (pending_bits >= 8 || pending_bits != ones_run) {
    return Error("hpack: bad huffman padding");
  }
  return Error::Success();
}

void PutU32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>((v >> 24) & 0xFF));
  out->push_back(static_cast<char>((v >> 16) & 0xFF));
  out->push_back(static_cast<char>((v >> 8) & 0xFF));
  out->push_back(static_cast<char>(v & 0xFF));
}

// HPACK integer encoding with N-bit prefix, high bits `pattern`
void EncodeInt(std::string* out, uint8_t pattern, int prefix_bits, uint64_t v) {
  uint64_t limit = (1u << prefix_bits) - 1;
  if (v < limit) {
    out->push_back(static_cast<char>(pattern | v));
    return;
  }
  out->push_back(static_cast<char>(pattern | limit));
  v -= limit;
  while (v >= 128) {
    out->push_back(static_cast<char>((v & 0x7F) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

// header field as "literal without indexing, new name" — keeps the encoder
// stateless (the decoder side still handles peers that use dynamic tables)
void EncodeLiteralHeader(
    std::string* out, const std::string& name, const std::string& value) {
  out->push_back('\0');  // 0000 0000: literal without indexing, new name
  EncodeInt(out, 0x00, 7, name.size());
  out->append(name);
  EncodeInt(out, 0x00, 7, value.size());
  out->append(value);
}

}  // namespace

// ---------------------------------------------------------------------------
// HpackDecoder
// ---------------------------------------------------------------------------

HpackDecoder::HpackDecoder() = default;

Error HpackDecoder::DecodeInt(
    const uint8_t** p, const uint8_t* end, int prefix_bits, uint64_t* out) {
  if (*p >= end) return Error("hpack: truncated integer");
  uint64_t limit = (1u << prefix_bits) - 1;
  uint64_t v = **p & limit;
  ++*p;
  if (v < limit) {
    *out = v;
    return Error::Success();
  }
  int shift = 0;
  while (*p < end) {
    uint8_t b = **p;
    ++*p;
    v += static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return Error::Success();
    }
    shift += 7;
    if (shift > 62) break;
  }
  return Error("hpack: malformed integer");
}

Error HpackDecoder::DecodeString(
    const uint8_t** p, const uint8_t* end, std::string* out) {
  if (*p >= end) return Error("hpack: truncated string");
  bool huffman = (**p & 0x80) != 0;
  uint64_t length;
  Error err = DecodeInt(p, end, 7, &length);
  if (err) return err;
  if (length > static_cast<uint64_t>(end - *p)) {
    return Error("hpack: string overruns block");
  }
  if (huffman) {
    err = HuffmanDecode(*p, length, out);
    if (err) return err;
  } else {
    out->assign(reinterpret_cast<const char*>(*p), length);
  }
  *p += length;
  return Error::Success();
}

Error HpackDecoder::Lookup(
    uint64_t index, std::string* name, std::string* value) {
  if (index == 0) return Error("hpack: index 0");
  constexpr size_t kStaticCount = sizeof(kStaticTable) / sizeof(kStaticTable[0]);
  if (index <= kStaticCount) {
    *name = kStaticTable[index - 1].name;
    *value = kStaticTable[index - 1].value;
    return Error::Success();
  }
  size_t dyn = index - kStaticCount - 1;
  if (dyn >= dynamic_.size()) return Error("hpack: index out of range");
  *name = dynamic_[dyn].first;
  *value = dynamic_[dyn].second;
  return Error::Success();
}

void HpackDecoder::Insert(const std::string& name, const std::string& value) {
  size_t entry = name.size() + value.size() + 32;
  dynamic_.insert(dynamic_.begin(), {name, value});
  dynamic_size_ += entry;
  EvictTo(max_size_);
}

void HpackDecoder::EvictTo(size_t target) {
  while (dynamic_size_ > target && !dynamic_.empty()) {
    const auto& back = dynamic_.back();
    dynamic_size_ -= back.first.size() + back.second.size() + 32;
    dynamic_.pop_back();
  }
}

Error HpackDecoder::Decode(
    const uint8_t* data, size_t size, HeaderList* out) {
  const uint8_t* p = data;
  const uint8_t* end = data + size;
  while (p < end) {
    uint8_t b = *p;
    if (b & 0x80) {  // indexed
      uint64_t index;
      Error err = DecodeInt(&p, end, 7, &index);
      if (err) return err;
      std::string name, value;
      err = Lookup(index, &name, &value);
      if (err) return err;
      out->emplace_back(std::move(name), std::move(value));
    } else if ((b & 0xC0) == 0x40) {  // literal with incremental indexing
      uint64_t index;
      Error err = DecodeInt(&p, end, 6, &index);
      if (err) return err;
      std::string name, value, ignored;
      if (index != 0) {
        err = Lookup(index, &name, &ignored);
        if (err) return err;
      } else {
        err = DecodeString(&p, end, &name);
        if (err) return err;
      }
      err = DecodeString(&p, end, &value);
      if (err) return err;
      Insert(name, value);
      out->emplace_back(std::move(name), std::move(value));
    } else if ((b & 0xE0) == 0x20) {  // dynamic table size update
      uint64_t new_size;
      Error err = DecodeInt(&p, end, 5, &new_size);
      if (err) return err;
      if (new_size > protocol_max_size_) {
        return Error("hpack: table size update beyond SETTINGS limit");
      }
      max_size_ = new_size;
      EvictTo(max_size_);
    } else {  // literal without indexing (0000) / never indexed (0001)
      uint64_t index;
      Error err = DecodeInt(&p, end, 4, &index);
      if (err) return err;
      std::string name, value, ignored;
      if (index != 0) {
        err = Lookup(index, &name, &ignored);
        if (err) return err;
      } else {
        err = DecodeString(&p, end, &name);
        if (err) return err;
      }
      err = DecodeString(&p, end, &value);
      if (err) return err;
      out->emplace_back(std::move(name), std::move(value));
    }
  }
  return Error::Success();
}

// ---------------------------------------------------------------------------
// Connection
// ---------------------------------------------------------------------------

Connection::Connection(const std::string& host_port) : host_port_(host_port) {}

Connection::~Connection() {
  if (fd_ >= 0) close(fd_);
}

Error Connection::Connect(
    std::unique_ptr<Connection>* conn, const std::string& url,
    int64_t timeout_ms, const tls::TlsOptions* tls_options) {
  if (url.empty()) return Error("h2: empty server url");
  // scheme prefix: https:// selects TLS (explicit options can also force it)
  std::string host_port = url;
  bool use_tls = tls_options != nullptr && tls_options->use_tls;
  std::string default_port = "80";
  if (host_port.rfind("https://", 0) == 0) {
    host_port = host_port.substr(8);
    use_tls = true;
    default_port = "443";
  } else if (host_port.rfind("http://", 0) == 0) {
    host_port = host_port.substr(7);
  }
  if (host_port.empty()) return Error("h2: empty server url");
  std::string host = host_port;
  std::string port = default_port;
  size_t bracket = host_port.rfind("]:");
  if (bracket != std::string::npos && host_port.front() == '[') {
    // [v6-literal]:port
    host = host_port.substr(1, bracket - 1);
    port = host_port.substr(bracket + 2);
  } else {
    size_t colon = host_port.rfind(':');
    if (colon != std::string::npos &&
        host_port.find(':') == colon) {  // exactly one ':' => host:port
      host = host_port.substr(0, colon);
      port = host_port.substr(colon + 1);
    } else if (host_port.front() == '[' && host_port.back() == ']') {
      host = host_port.substr(1, host_port.size() - 2);
    } else if (colon != std::string::npos) {
      // multiple ':' without brackets is ambiguous (v6 host? host:port with
      // a stray colon?) — require [v6]:port rather than guessing
      return Error(
          "h2: ambiguous url '" + host_port +
          "' (IPv6 literals must be bracketed: [addr] or [addr]:port)");
    }
  }
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* result = nullptr;
  int rc = getaddrinfo(host.c_str(), port.c_str(), &hints, &result);
  if (rc != 0) {
    return Error(
        "failed to resolve " + host_port + ": " + gai_strerror(rc));
  }
  int fd = -1;
  for (struct addrinfo* ai = result; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // non-blocking from the start: connect honors timeout_ms, and
    // send/recv surface EAGAIN so per-call deadlines work
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
    int rc2 = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc2 == 0) break;
    if (errno == EINPROGRESS) {
      struct pollfd pfd = {fd, POLLOUT, 0};
      int ready = poll(&pfd, 1, static_cast<int>(timeout_ms));
      int soerr = 0;
      socklen_t len = sizeof(soerr);
      if (ready > 0 &&
          getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &len) == 0 &&
          soerr == 0) {
        break;
      }
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(result);
  if (fd < 0) return Error("failed to connect to " + host_port);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  auto c = std::unique_ptr<Connection>(new Connection(host_port));
  c->fd_ = fd;
  if (use_tls) {
    tls::TlsOptions opts = tls_options != nullptr ? *tls_options
                                                  : tls::TlsOptions{};
    Error terr =
        tls::TlsSession::Create(&c->tls_, fd, host, opts, timeout_ms);
    if (terr) return terr;
    if (c->tls_->Alpn() != "h2") {
      return Error(
          "TLS peer did not negotiate h2 (ALPN: '" + c->tls_->Alpn() +
          "') — gRPC requires HTTP/2");
    }
  }
  Error err = c->Handshake(timeout_ms);
  if (err) return err;
  c->alive_ = true;
  *conn = std::move(c);
  return Error::Success();
}

ssize_t Connection::IoSend(const void* data, size_t size) {
  if (tls_ != nullptr) return tls_->Send(data, size);
  return send(fd_, data, size, MSG_NOSIGNAL);
}

ssize_t Connection::IoRecv(void* buf, size_t size) {
  if (tls_ != nullptr) return tls_->Recv(buf, size);
  return recv(fd_, buf, size, MSG_DONTWAIT);
}

short Connection::IoPollEvents(short plain) const {
  // a TLS session mid-renegotiation can need POLLIN to finish a write and
  // vice versa — it tracks which event unblocks each half's last EAGAIN
  if (tls_ == nullptr) return plain;
  return plain == POLLOUT ? tls_->SendPollEvents() : tls_->RecvPollEvents();
}

Error Connection::Handshake(int64_t timeout_ms) {
  // preface + SETTINGS(ENABLE_PUSH=0, INITIAL_WINDOW_SIZE=kRecvWindow,
  // MAX_FRAME_SIZE=1MiB) + connection window bump
  std::string out(kPreface, sizeof(kPreface) - 1);
  std::string settings;
  auto setting = [&settings](uint16_t id, uint32_t v) {
    settings.push_back(static_cast<char>(id >> 8));
    settings.push_back(static_cast<char>(id & 0xFF));
    PutU32(&settings, v);
  };
  setting(0x2, 0);                                   // ENABLE_PUSH off
  setting(0x4, static_cast<uint32_t>(kRecvWindow));  // INITIAL_WINDOW_SIZE
  setting(0x5, 1 << 20);                             // MAX_FRAME_SIZE
  // frame header
  uint32_t len = static_cast<uint32_t>(settings.size());
  out.push_back(static_cast<char>((len >> 16) & 0xFF));
  out.push_back(static_cast<char>((len >> 8) & 0xFF));
  out.push_back(static_cast<char>(len & 0xFF));
  out.push_back(static_cast<char>(kSettings));
  out.push_back(0);  // flags
  PutU32(&out, 0);   // stream 0
  out.append(settings);
  // connection-level WINDOW_UPDATE to kRecvWindow
  out.push_back(0);
  out.push_back(0);
  out.push_back(4);
  out.push_back(static_cast<char>(kWindowUpdate));
  out.push_back(0);
  PutU32(&out, 0);
  PutU32(&out, static_cast<uint32_t>(kRecvWindow - 65535));
  Error err = SendAll(out.data(), out.size(), timeout_ms);
  if (err) return err;
  // the server's SETTINGS arrives with the first RecvFrame calls; no need
  // to block on it here (RFC allows requests before the ACK round trip)
  return Error::Success();
}

Error Connection::SendAll(const void* data, size_t size, int64_t timeout_ms) {
  const char* p = static_cast<const char*>(data);
  size_t remaining = size;
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  while (remaining > 0) {
    ssize_t n = IoSend(p, remaining);
    if (n > 0) {
      p += n;
      remaining -= static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      struct pollfd pfd = {fd_, IoPollEvents(POLLOUT), 0};
      int wait = deadline ? static_cast<int>(deadline - NowMs()) : 1000;
      if (deadline && wait <= 0) return Error("send timeout");
      poll(&pfd, 1, wait);
      continue;
    }
    alive_ = false;
    return Error(
        std::string("connection write failed: ") + strerror(errno));
  }
  return Error::Success();
}

Error Connection::SendFrame(
    uint8_t type, uint8_t flags, int32_t stream_id, const void* payload,
    size_t size, int64_t timeout_ms) {
  // one contiguous buffer + one lock: a frame is never interleaved with
  // another thread's bytes (the streaming reader sends WINDOW_UPDATEs
  // concurrently with application DATA)
  std::string frame;
  frame.reserve(9 + size);
  frame.push_back(static_cast<char>((size >> 16) & 0xFF));
  frame.push_back(static_cast<char>((size >> 8) & 0xFF));
  frame.push_back(static_cast<char>(size & 0xFF));
  frame.push_back(static_cast<char>(type));
  frame.push_back(static_cast<char>(flags));
  PutU32(&frame, static_cast<uint32_t>(stream_id));
  if (size > 0) frame.append(static_cast<const char*>(payload), size);
  std::lock_guard<std::mutex> lock(send_mutex_);
  return SendAll(frame.data(), frame.size(), timeout_ms);
}

// Reads exactly one frame from the socket and dispatches it into stream /
// connection state. Caller holds recv_mutex_; state mutations take
// state_mutex_, and every dispatched frame notifies frame_cv_ so threads
// blocked in PumpOne can re-check their stream.
Error Connection::RecvFrameLocked(int64_t timeout_ms) {
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  auto fill = [&](size_t need) -> Error {
    while (recv_buffer_.size() < need) {
      char buf[65536];
      ssize_t n = IoRecv(buf, sizeof(buf));
      if (n > 0) {
        recv_buffer_.append(buf, static_cast<size_t>(n));
        continue;
      }
      if (n == 0) {
        alive_ = false;
        return Error(
            goaway_debug_.empty()
                ? "connection closed by peer"
                : "connection closed by peer (GOAWAY: " + goaway_debug_ + ")");
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd pfd = {fd_, IoPollEvents(POLLIN), 0};
        int wait = deadline ? static_cast<int>(deadline - NowMs()) : 1000;
        if (deadline && wait <= 0) return Error("Deadline Exceeded");
        poll(&pfd, 1, wait);
        continue;
      }
      alive_ = false;
      return Error(std::string("connection read failed: ") + strerror(errno));
    }
    return Error::Success();
  };

  Error err = fill(9);
  if (err) return err;
  const uint8_t* h = reinterpret_cast<const uint8_t*>(recv_buffer_.data());
  size_t length = (static_cast<size_t>(h[0]) << 16) |
                  (static_cast<size_t>(h[1]) << 8) | h[2];
  uint8_t type = h[3];
  uint8_t flags = h[4];
  int32_t stream_id = static_cast<int32_t>(
      ((static_cast<uint32_t>(h[5]) << 24) | (static_cast<uint32_t>(h[6]) << 16) |
       (static_cast<uint32_t>(h[7]) << 8) | h[8]) &
      0x7FFFFFFF);
  err = fill(9 + length);
  if (err) return err;
  const uint8_t* payload =
      reinterpret_cast<const uint8_t*>(recv_buffer_.data()) + 9;

  switch (type) {
    case kData: {
      size_t data_len = length;
      const uint8_t* data = payload;
      if (flags & kFlagPadded) {
        if (data_len < 1) return Error("h2: padded DATA too short");
        uint8_t pad = data[0];
        if (1u + pad > data_len) return Error("h2: DATA padding overflow");
        data += 1;
        data_len -= 1 + pad;
      }
      {
        std::lock_guard<std::mutex> lock(state_mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) {
          it->second.body.append(
              reinterpret_cast<const char*>(data), data_len);
          if (flags & kFlagEndStream) it->second.closed = true;
        }
      }
      // replenish both windows for the full frame length (outside the
      // state lock: SendFrame takes the send lock)
      if (length > 0) {
        std::string wu;
        PutU32(&wu, static_cast<uint32_t>(length));
        SendFrame(kWindowUpdate, 0, 0, wu.data(), wu.size(), timeout_ms);
        if (!(flags & kFlagEndStream)) {
          SendFrame(
              kWindowUpdate, 0, stream_id, wu.data(), wu.size(), timeout_ms);
        }
      }
      break;
    }
    case kHeaders: {
      size_t block_len = length;
      const uint8_t* block = payload;
      if (flags & kFlagPadded) {
        if (block_len < 1) return Error("h2: padded HEADERS too short");
        uint8_t pad = block[0];
        block += 1;
        if (1u + pad > block_len) return Error("h2: HEADERS padding overflow");
        block_len -= 1 + pad;
      }
      if (flags & kFlagPriority) {
        if (block_len < 5) return Error("h2: HEADERS priority too short");
        block += 5;
        block_len -= 5;
      }
      if (!(flags & kFlagEndHeaders)) {
        // CONTINUATION support: accumulate until END_HEADERS. Our peers'
        // header blocks are tiny; treat fragmentation as a hard error for
        // now rather than carrying half-finished decode state.
        return Error("h2: fragmented header block (CONTINUATION) unsupported");
      }
      HeaderList decoded;
      Error derr = hpack_.Decode(block, block_len, &decoded);
      if (derr) return derr;
      {
        std::lock_guard<std::mutex> lock(state_mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) {
          for (auto& kv : decoded) {
            it->second.headers[kv.first] = kv.second;
          }
          it->second.headers_done = true;
          if (flags & kFlagEndStream) it->second.closed = true;
        }
      }
      break;
    }
    case kRstStream: {
      if (length >= 4) {
        uint32_t code = (static_cast<uint32_t>(payload[0]) << 24) |
                        (static_cast<uint32_t>(payload[1]) << 16) |
                        (static_cast<uint32_t>(payload[2]) << 8) | payload[3];
        std::lock_guard<std::mutex> lock(state_mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) {
          it->second.closed = true;
          it->second.error =
              Error("stream reset by peer (code " + std::to_string(code) + ")");
        }
      }
      break;
    }
    case kSettings: {
      if (!(flags & kFlagAck)) {
        for (size_t off = 0; off + 6 <= length; off += 6) {
          uint16_t id = (static_cast<uint16_t>(payload[off]) << 8) |
                        payload[off + 1];
          uint32_t value = (static_cast<uint32_t>(payload[off + 2]) << 24) |
                           (static_cast<uint32_t>(payload[off + 3]) << 16) |
                           (static_cast<uint32_t>(payload[off + 4]) << 8) |
                           payload[off + 5];
          if (id == 0x1) {
            // HEADER_TABLE_SIZE governs what the PEER's decoder accepts,
            // i.e. our (stateless) encoder — not our decoder, whose limit
            // is what WE advertise (we never send the setting: 4096).
          } else if (id == 0x4) {  // INITIAL_WINDOW_SIZE
            std::lock_guard<std::mutex> lock(state_mutex_);
            int64_t delta = static_cast<int64_t>(value) - peer_initial_window_;
            peer_initial_window_ = value;
            for (auto& s : streams_) s.second.send_window += delta;
          } else if (id == 0x3) {  // MAX_CONCURRENT_STREAMS
            std::lock_guard<std::mutex> lock(state_mutex_);
            peer_max_concurrent_streams_ = value;
          } else if (id == 0x5) {  // MAX_FRAME_SIZE
            std::lock_guard<std::mutex> lock(state_mutex_);
            peer_max_frame_size_ = value;
          }
        }
        SendFrame(kSettings, kFlagAck, 0, nullptr, 0, timeout_ms);
      }
      break;
    }
    case kPing: {
      if (!(flags & kFlagAck) && length == 8) {
        SendFrame(kPing, kFlagAck, 0, payload, 8, timeout_ms);
      }
      break;
    }
    case kGoaway: {
      if (length >= 8) {
        int32_t last_stream_id = static_cast<int32_t>(
            ((static_cast<uint32_t>(payload[0]) << 24) |
             (static_cast<uint32_t>(payload[1]) << 16) |
             (static_cast<uint32_t>(payload[2]) << 8) | payload[3]) &
            0x7FFFFFFF);
        // Streams above last_stream_id will NEVER complete (RFC 7540
        // §6.8): error them now so waiters get a typed failure instead of
        // blocking until the peer closes the socket; streams at or below
        // the id may still finish normally. New opens must fail fast.
        // goaway_debug_ is written under state_mutex_: StreamOpen/PumpOne
        // read it under the same lock from other threads.
        std::lock_guard<std::mutex> lock(state_mutex_);
        goaway_debug_.assign(
            reinterpret_cast<const char*>(payload + 8), length - 8);
        goaway_received_ = true;
        for (auto& kv : streams_) {
          if (kv.first > last_stream_id && !kv.second.closed) {
            kv.second.error = Error(
                "stream rejected: peer sent GOAWAY" +
                (goaway_debug_.empty() ? std::string()
                                       : " (" + goaway_debug_ + ")"));
            kv.second.closed = true;
          }
        }
      }
      break;
    }
    case kWindowUpdate: {
      if (length >= 4) {
        uint32_t inc = ((static_cast<uint32_t>(payload[0]) << 24) |
                        (static_cast<uint32_t>(payload[1]) << 16) |
                        (static_cast<uint32_t>(payload[2]) << 8) | payload[3]) &
                       0x7FFFFFFF;
        std::lock_guard<std::mutex> lock(state_mutex_);
        if (stream_id == 0) {
          conn_send_window_ += inc;
        } else {
          auto it = streams_.find(stream_id);
          if (it != streams_.end()) it->second.send_window += inc;
        }
      }
      break;
    }
    case kPushPromise:
      return Error("h2: unexpected PUSH_PROMISE (push is disabled)");
    case kContinuation:
      return Error("h2: unexpected CONTINUATION frame");
    default:
      break;  // unknown frame types are ignored (RFC 7540 §4.1)
  }
  recv_buffer_.erase(0, 9 + length);
  {  // wake any thread waiting for this stream's state to change
    std::lock_guard<std::mutex> lock(state_mutex_);
    frame_cv_.notify_all();
  }
  return Error::Success();
}

// One unit of progress toward new frames: become the receiver, or wait for
// the current receiver to dispatch something.
Error Connection::PumpOne(int64_t timeout_ms) {
  std::unique_lock<std::mutex> rl(recv_mutex_, std::try_to_lock);
  if (rl.owns_lock()) {
    return RecvFrameLocked(timeout_ms);
  }
  std::unique_lock<std::mutex> sl(state_mutex_);
  frame_cv_.wait_for(
      sl, std::chrono::milliseconds(
              timeout_ms > 0 ? std::min<int64_t>(timeout_ms, 100) : 100));
  if (!alive_) {
    return Error(
        goaway_debug_.empty()
            ? "connection closed by peer"
            : "connection closed by peer (GOAWAY: " + goaway_debug_ + ")");
  }
  return Error::Success();
}

Error Connection::StreamOpen(
    const std::string& path, const HeaderList& headers, int32_t* stream_id) {
  if (!alive_) return Error("connection is closed");
  std::string block;
  EncodeLiteralHeader(&block, ":method", "POST");
  EncodeLiteralHeader(&block, ":scheme", tls_ != nullptr ? "https" : "http");
  EncodeLiteralHeader(&block, ":authority", host_port_);
  EncodeLiteralHeader(&block, ":path", path);
  for (const auto& kv : headers) {
    std::string name = kv.first;
    for (auto& c : name) c = static_cast<char>(tolower(c));
    EncodeLiteralHeader(&block, name, kv.second);
  }
  if (block.size() > 16000) return Error("h2: header block too large");
  int32_t id;
  std::string frame;
  frame.reserve(9 + block.size());
  {
    // send_mutex_ held across BOTH the id allocation and the HEADERS write:
    // ids must hit the wire strictly increasing (RFC 7540 §5.1.1), and two
    // threads opening streams concurrently could otherwise interleave
    // allocation order with write order and tear the connection down with
    // PROTOCOL_ERROR.
    std::lock_guard<std::mutex> send_lock(send_mutex_);
    {
      // register the stream before its HEADERS can be answered
      std::lock_guard<std::mutex> lock(state_mutex_);
      if (goaway_received_) {
        return Error(
            "connection is shutting down (GOAWAY received" +
            (goaway_debug_.empty() ? std::string()
                                   : ": " + goaway_debug_) + ")");
      }
      id = next_stream_id_;
      next_stream_id_ += 2;
      streams_[id].send_window = peer_initial_window_;
    }
    size_t size = block.size();
    frame.push_back(static_cast<char>((size >> 16) & 0xFF));
    frame.push_back(static_cast<char>((size >> 8) & 0xFF));
    frame.push_back(static_cast<char>(size & 0xFF));
    frame.push_back(static_cast<char>(kHeaders));
    frame.push_back(static_cast<char>(kFlagEndHeaders));
    PutU32(&frame, static_cast<uint32_t>(id));
    frame.append(block);
    Error err = SendAll(frame.data(), frame.size(), 0);
    if (err) {
      std::lock_guard<std::mutex> lock(state_mutex_);
      streams_.erase(id);
      return err;
    }
  }
  *stream_id = id;
  return Error::Success();
}

Error Connection::StreamSend(
    int32_t stream_id, const void* data, size_t size, bool end_stream,
    int64_t timeout_ms) {
  if (!alive_) return Error("connection is closed");
  const char* p = static_cast<const char*>(data);
  size_t remaining = size;
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  do {
    size_t chunk;
    {
      // respect stream + connection flow control and the peer frame limit
      std::lock_guard<std::mutex> lock(state_mutex_);
      auto it = streams_.find(stream_id);
      if (it == streams_.end()) return Error("h2: unknown stream");
      if (it->second.error) return it->second.error;
      int64_t budget = std::min(it->second.send_window, conn_send_window_);
      if (remaining > 0 && budget <= 0) {
        chunk = 0;
      } else {
        chunk = remaining;
        if (static_cast<int64_t>(chunk) > budget) {
          chunk = static_cast<size_t>(budget);
        }
        if (chunk > static_cast<size_t>(peer_max_frame_size_)) {
          chunk = static_cast<size_t>(peer_max_frame_size_);
        }
        it->second.send_window -= static_cast<int64_t>(chunk);
        conn_send_window_ -= static_cast<int64_t>(chunk);
      }
    }
    if (remaining > 0 && chunk == 0) {
      // out of window: drain frames until a WINDOW_UPDATE arrives
      int64_t wait = deadline ? deadline - NowMs() : 1000;
      if (deadline && wait <= 0) return Error("Deadline Exceeded");
      Error err = PumpOne(wait);
      if (err) return err;
      continue;
    }
    bool last = (chunk == remaining) && end_stream;
    Error err = SendFrame(
        kData, last ? kFlagEndStream : 0, stream_id, p, chunk, timeout_ms);
    if (err) {
      // the window reservation is lost with the connection; no rollback
      return err;
    }
    p += chunk;
    remaining -= chunk;
  } while (remaining > 0);
  return Error::Success();
}

Error Connection::StreamRecv(
    int32_t stream_id, std::string* body,
    std::map<std::string, std::string>* headers, bool* closed,
    int64_t timeout_ms) {
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  while (true) {
    {
      std::lock_guard<std::mutex> lock(state_mutex_);
      auto it = streams_.find(stream_id);
      if (it == streams_.end()) return Error("h2: unknown stream");
      if (!it->second.body.empty() || it->second.closed) {
        if (it->second.error) {
          // terminal: reap the entry, or error-heavy callers leak one map
          // slot per failed RPC on a long-lived multiplexed connection
          Error stream_err = it->second.error;
          streams_.erase(it);
          return stream_err;
        }
        body->append(it->second.body);
        it->second.body.clear();
        for (const auto& kv : it->second.headers) {
          (*headers)[kv.first] = kv.second;
        }
        *closed = it->second.closed;
        if (it->second.closed) streams_.erase(it);
        return Error::Success();
      }
    }
    int64_t wait = deadline ? deadline - NowMs() : 0;
    if (deadline && wait <= 0) return Error("Deadline Exceeded");
    Error err = PumpOne(wait);
    if (err) return err;
  }
}

Error Connection::StreamReset(int32_t stream_id) {
  std::string payload;
  PutU32(&payload, 0x8);  // CANCEL
  Error err =
      SendFrame(kRstStream, 0, stream_id, payload.data(), payload.size(), 0);
  std::lock_guard<std::mutex> lock(state_mutex_);
  streams_.erase(stream_id);
  return err;
}

Error Connection::StreamWaitAny(
    const std::vector<int32_t>& stream_ids, int32_t* ready_id,
    int64_t timeout_ms) {
  // Completion-queue primitive: pump frames until ANY of the given streams
  // is closed (or carries a stream error). Frames for every stream are
  // dispatched as they arrive regardless of which one we return first.
  if (stream_ids.empty()) return Error("h2: StreamWaitAny on no streams");
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  while (true) {
    {
      std::lock_guard<std::mutex> lock(state_mutex_);
      for (int32_t id : stream_ids) {
        auto it = streams_.find(id);
        if (it == streams_.end()) {
          // already reaped (or reset) — surface it so the caller drops it
          *ready_id = id;
          return Error::Success();
        }
        if (it->second.closed || it->second.error) {
          *ready_id = id;
          return Error::Success();
        }
      }
    }
    int64_t wait = deadline ? deadline - NowMs() : 0;
    if (deadline && wait <= 0) return Error("Deadline Exceeded");
    Error err = PumpOne(wait);
    if (err) return err;
  }
}

Error Connection::PumpUntil(int32_t stream_id, int64_t timeout_ms) {
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  while (true) {
    {
      std::lock_guard<std::mutex> lock(state_mutex_);
      auto it = streams_.find(stream_id);
      if (it == streams_.end()) return Error("h2: stream vanished");
      if (it->second.closed) return Error::Success();
    }
    int64_t wait = deadline ? deadline - NowMs() : 0;
    if (deadline && wait <= 0) return Error("Deadline Exceeded");
    Error err = PumpOne(wait);
    if (err) return err;
  }
}

Error Connection::Request(
    const std::string& path, const HeaderList& headers,
    const std::string& body, Response* out, int64_t timeout_ms) {
  // ONE deadline across all phases: passing timeout_ms to each phase
  // independently would let worst-case wall time run to ~2x the request.
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : 0;
  auto remaining = [deadline]() -> int64_t {
    if (deadline == 0) return 0;  // no timeout
    int64_t left = deadline - NowMs();
    return left > 0 ? left : -1;  // -1: expired (0 would mean "no timeout")
  };
  int32_t stream_id;
  Error err = StreamOpen(path, headers, &stream_id);
  if (err) return err;
  int64_t left = remaining();
  if (left < 0) return Error("Deadline Exceeded");
  err = StreamSend(stream_id, body.data(), body.size(), true, left);
  if (err) return err;
  left = remaining();
  if (left < 0) return Error("Deadline Exceeded");
  err = PumpUntil(stream_id, left);
  if (err) return err;
  std::lock_guard<std::mutex> lock(state_mutex_);
  auto it = streams_.find(stream_id);
  if (it == streams_.end()) return Error("h2: stream vanished");
  if (it->second.error) {
    Error stream_err = it->second.error;
    streams_.erase(it);
    return stream_err;
  }
  out->headers = std::move(it->second.headers);
  out->body = std::move(it->second.body);
  auto status_it = out->headers.find(":status");
  if (status_it != out->headers.end()) {
    out->status = atoi(status_it->second.c_str());
  }
  streams_.erase(it);
  return Error::Success();
}

}  // namespace h2
}  // namespace client_tpu
