// Decoupled LLM generation over bi-di GRPC streaming — the native
// counterpart of the LLM-streaming Python example. Role parity with the
// reference's src/c++/examples/simple_grpc_async_infer_client.cc (async
// requests in flight, completion out of band) composed with its decoupled
// streaming examples: ONE stream carries the request and N incremental
// responses (NEXT_TOKEN/INDEX per generated token), the client consumes
// tokens as they arrive, and a final-response marker ends the exchange.
//
// Build: part of the normal native build (cmake -S native -B native/build).
// Run:   simple_grpc_async_stream_client [-u host:port] [-n max_tokens]
//        (default URL from $CLIENT_TPU_TEST_GRPC_URL, else 127.0.0.1:8001)

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                                  \
  do {                                                                       \
    const tc::Error err = (X);                                               \
    if (!err.IsOk()) {                                                       \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                              \
    }                                                                        \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8001";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_GRPC_URL")) {
    url = env;
  }
  int32_t max_tokens = 8;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    } else if (std::strcmp(argv[i], "-n") == 0 && i + 1 < argc) {
      max_tokens = std::atoi(argv[++i]);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url),
      "unable to create grpc client");

  // The stream callback runs on the reader thread: collect tokens under a
  // lock and wake the main thread when the final-response marker lands.
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> tokens;
  std::vector<int32_t> indexes;
  bool done = false;
  std::string stream_error;

  FAIL_IF_ERR(
      client->StartStream([&](tc::InferResult* result, const tc::Error& err) {
        std::unique_ptr<tc::InferResult> owned(result);
        std::lock_guard<std::mutex> lock(mu);
        if (err) {
          stream_error = err.Message();
          done = true;
          cv.notify_one();
          return;
        }
        if (owned == nullptr) {
          return;
        }
        bool is_final = false;
        (void)owned->IsFinalResponse(&is_final);
        bool is_null = false;
        (void)owned->IsNullResponse(&is_null);
        if (!is_null) {
          const uint8_t* buf = nullptr;
          size_t nbytes = 0;
          if (!owned->RawData("NEXT_TOKEN", &buf, &nbytes) &&
              nbytes == sizeof(int32_t)) {
            int32_t tok;
            std::memcpy(&tok, buf, sizeof(tok));
            tokens.push_back(tok);
          }
          if (!owned->RawData("INDEX", &buf, &nbytes) &&
              nbytes == sizeof(int32_t)) {
            int32_t idx;
            std::memcpy(&idx, buf, sizeof(idx));
            indexes.push_back(idx);
          }
        }
        if (is_final) {
          done = true;
          cv.notify_one();
        }
      }),
      "starting stream");

  // prompt + generation budget; the decoupled model answers with one
  // response per generated token on the same stream
  std::vector<int32_t> prompt{1, 2, 3};
  tc::InferInput* prompt_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(
          &prompt_raw, "TOKENS", {1, static_cast<int64_t>(prompt.size())},
          "INT32"),
      "creating TOKENS");
  std::unique_ptr<tc::InferInput> prompt_in(prompt_raw);
  FAIL_IF_ERR(
      prompt_in->AppendRaw(
          reinterpret_cast<const uint8_t*>(prompt.data()),
          prompt.size() * sizeof(int32_t)),
      "setting TOKENS");

  tc::InferInput* max_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&max_raw, "MAX_TOKENS", {1}, "INT32"),
      "creating MAX_TOKENS");
  std::unique_ptr<tc::InferInput> max_in(max_raw);
  FAIL_IF_ERR(
      max_in->AppendRaw(
          reinterpret_cast<const uint8_t*>(&max_tokens), sizeof(max_tokens)),
      "setting MAX_TOKENS");

  tc::InferOptions options("tiny_lm_generate");
  options.request_id = "stream-1";
  options.enable_empty_final_response = true;
  FAIL_IF_ERR(
      client->AsyncStreamInfer(options, {prompt_in.get(), max_in.get()}),
      "sending stream request");

  {
    std::unique_lock<std::mutex> lock(mu);
    if (!cv.wait_for(
            lock, std::chrono::seconds(60), [&] { return done; })) {
      std::cerr << "error: stream timed out" << std::endl;
      return 1;
    }
  }
  FAIL_IF_ERR(client->StopStream(), "stopping stream");

  if (!stream_error.empty()) {
    std::cerr << "error: stream callback: " << stream_error << std::endl;
    return 1;
  }
  // no END_ID is sent, so generation must run the full budget
  if (tokens.size() != static_cast<size_t>(max_tokens)) {
    std::cerr << "error: expected " << max_tokens << " tokens, got "
              << tokens.size() << std::endl;
    return 1;
  }
  // incremental delivery contract: INDEX is the 0-based position of each
  // token, so the stream must arrive in order with no gaps
  for (size_t i = 0; i < indexes.size(); ++i) {
    if (indexes[i] != static_cast<int32_t>(i)) {
      std::cerr << "error: response " << i << " carried INDEX " << indexes[i]
                << std::endl;
      return 1;
    }
  }

  std::cout << "generated " << tokens.size() << " tokens:";
  for (int32_t tok : tokens) {
    std::cout << " " << tok;
  }
  std::cout << std::endl;
  std::cout << "PASS : simple_grpc_async_stream_client" << std::endl;
  return 0;
}
