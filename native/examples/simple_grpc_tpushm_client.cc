// TPU shared-memory inference from the native GRPC client — the
// accelerator data plane. Role parity with the reference's
// src/c++/examples/simple_grpc_cudashm_client.cc: inputs are written into
// a device-backed region, outputs land in another, and the wire carries
// only tensor METADATA (name/shape/region offsets) — the payload never
// rides the request body. On TPU the handles are base64-JSON
// (Python-interoperable) instead of CUDA IPC handles; colocated regions
// never leave HBM.
//
// Build: part of the normal native build (cmake -S native -B native/build).
// Run:   simple_grpc_tpushm_client [-u host:port]
//        (default URL from $CLIENT_TPU_TEST_GRPC_URL, else 127.0.0.1:8001)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"
#include "client_tpu/tpu_shm.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                                  \
  do {                                                                       \
    const tc::Error err = (X);                                               \
    if (!err.IsOk()) {                                                       \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                              \
    }                                                                        \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8001";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_GRPC_URL")) {
    url = env;
  }
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url),
      "unable to create grpc client");

  // one region for both inputs (offsets 0 and 64), one for both outputs
  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  tc::TpuShmRegion* input_region_raw = nullptr;
  FAIL_IF_ERR(
      tc::TpuShmRegion::Create(
          &input_region_raw, "example_tpushm_in", 2 * kTensorBytes),
      "creating input region");
  std::unique_ptr<tc::TpuShmRegion> input_region(input_region_raw);
  tc::TpuShmRegion* output_region_raw = nullptr;
  FAIL_IF_ERR(
      tc::TpuShmRegion::Create(
          &output_region_raw, "example_tpushm_out", 2 * kTensorBytes),
      "creating output region");
  std::unique_ptr<tc::TpuShmRegion> output_region(output_region_raw);

  int32_t input0_data[16], input1_data[16];
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
    input1_data[i] = 1;
  }
  FAIL_IF_ERR(
      input_region->Write(input0_data, kTensorBytes, 0), "writing INPUT0");
  FAIL_IF_ERR(
      input_region->Write(input1_data, kTensorBytes, kTensorBytes),
      "writing INPUT1");

  // register via the serialized raw handle — the same handle a Python
  // client_tpu.utils.tpu_shared_memory region round-trips
  FAIL_IF_ERR(
      client->RegisterTpuSharedMemory(
          "example_tpushm_in", input_region->RawHandle(), 0,
          2 * kTensorBytes),
      "registering input region");
  FAIL_IF_ERR(
      client->RegisterTpuSharedMemory(
          "example_tpushm_out", output_region->RawHandle(), 0,
          2 * kTensorBytes),
      "registering output region");

  tc::InferInput* input0_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0_raw, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  std::unique_ptr<tc::InferInput> input0(input0_raw);
  FAIL_IF_ERR(
      input0->SetSharedMemory("example_tpushm_in", kTensorBytes, 0),
      "INPUT0 region ref");
  tc::InferInput* input1_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1_raw, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::unique_ptr<tc::InferInput> input1(input1_raw);
  FAIL_IF_ERR(
      input1->SetSharedMemory(
          "example_tpushm_in", kTensorBytes, kTensorBytes),
      "INPUT1 region ref");

  tc::InferRequestedOutput* output0_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0_raw, "OUTPUT0"),
      "creating OUTPUT0");
  std::unique_ptr<tc::InferRequestedOutput> output0(output0_raw);
  FAIL_IF_ERR(
      output0->SetSharedMemory("example_tpushm_out", kTensorBytes, 0),
      "OUTPUT0 region ref");
  tc::InferRequestedOutput* output1_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output1_raw, "OUTPUT1"),
      "creating OUTPUT1");
  std::unique_ptr<tc::InferRequestedOutput> output1(output1_raw);
  FAIL_IF_ERR(
      output1->SetSharedMemory(
          "example_tpushm_out", kTensorBytes, kTensorBytes),
      "OUTPUT1 region ref");

  tc::InferOptions options("simple");
  tc::InferResult* result_raw = nullptr;
  FAIL_IF_ERR(
      client->Infer(
          &result_raw, options, {input0.get(), input1.get()},
          {output0.get(), output1.get()}),
      "running inference");
  std::unique_ptr<tc::InferResult> result(result_raw);
  FAIL_IF_ERR(result->RequestStatus(), "inference response status");

  // results are read from the OUTPUT region, not the response body
  int32_t sums[16], diffs[16];
  FAIL_IF_ERR(
      output_region->Read(sums, kTensorBytes, 0), "reading OUTPUT0");
  FAIL_IF_ERR(
      output_region->Read(diffs, kTensorBytes, kTensorBytes),
      "reading OUTPUT1");
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != input0_data[i] + input1_data[i] ||
        diffs[i] != input0_data[i] - input1_data[i]) {
      std::cerr << "error: wrong result at " << i << ": " << sums[i] << ", "
                << diffs[i] << std::endl;
      return 1;
    }
    std::cout << input0_data[i] << " + " << input1_data[i] << " = " << sums[i]
              << "   " << input0_data[i] << " - " << input1_data[i] << " = "
              << diffs[i] << std::endl;
  }

  FAIL_IF_ERR(
      client->UnregisterTpuSharedMemory(""), "unregistering regions");
  std::cout << "PASS : simple_grpc_tpushm_client" << std::endl;
  return 0;
}
