// Full system shared-memory lifecycle over GRPC — the zero-copy data
// plane a user graduates to after simple_grpc_infer_client. Role parity
// with the reference's src/c++/examples/simple_grpc_shm_client.cc
// (create → register → place tensors in the region → infer with NO tensor
// bytes on the wire → read outputs straight from the region → unregister →
// unlink; .py:90-183 is the matching Python walk-through).
//
// Run:   simple_grpc_shm_client [-u host:port] [-v]
//        (default URL from $CLIENT_TPU_TEST_GRPC_URL, else 127.0.0.1:8001)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"
#include "client_tpu/shm_utils.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                        \
  do {                                                             \
    const tc::Error err = (X);                                     \
    if (!err.IsOk()) {                                             \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                    \
    }                                                              \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8001";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_GRPC_URL")) {
    url = env;
  }
  bool verbose = false;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    } else if (std::strcmp(argv[i], "-v") == 0) {
      verbose = true;
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  constexpr size_t kInputBytes = 2 * kTensorBytes;   // INPUT0 + INPUT1
  constexpr size_t kOutputBytes = 2 * kTensorBytes;  // OUTPUT0 + OUTPUT1
  const std::string in_key = "/simple_grpc_shm_example_in";
  const std::string out_key = "/simple_grpc_shm_example_out";

  // a fresh run must not inherit a stale region from a crashed one
  (void)tc::UnlinkSharedMemoryRegion(in_key);
  (void)tc::UnlinkSharedMemoryRegion(out_key);

  // create + map both regions
  int in_fd = -1;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(in_key, kInputBytes, &in_fd),
      "creating input region");
  void* in_addr = nullptr;
  FAIL_IF_ERR(
      tc::MapSharedMemory(in_fd, 0, kInputBytes, &in_addr),
      "mapping input region");
  int out_fd = -1;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(out_key, kOutputBytes, &out_fd),
      "creating output region");
  void* out_addr = nullptr;
  FAIL_IF_ERR(
      tc::MapSharedMemory(out_fd, 0, kOutputBytes, &out_addr),
      "mapping output region");

  // tensor data goes INTO the region, not the request
  int32_t* in_region = reinterpret_cast<int32_t*>(in_addr);
  for (int i = 0; i < 16; ++i) {
    in_region[i] = i;       // INPUT0 at offset 0
    in_region[16 + i] = 1;  // INPUT1 at offset kTensorBytes
  }

  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "example_input_region", in_key, kInputBytes),
      "registering input region");
  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "example_output_region", out_key, kOutputBytes),
      "registering output region");
  tc::Json status;
  FAIL_IF_ERR(client->SystemSharedMemoryStatus(&status), "shm status");

  // inputs/outputs carry only {region, byte_size, offset}
  std::vector<int64_t> shape{1, 16};
  tc::InferInput* input0_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0_raw, "INPUT0", shape, "INT32"),
      "creating INPUT0");
  std::unique_ptr<tc::InferInput> input0(input0_raw);
  FAIL_IF_ERR(
      input0->SetSharedMemory("example_input_region", kTensorBytes, 0),
      "INPUT0 shm placement");
  tc::InferInput* input1_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1_raw, "INPUT1", shape, "INT32"),
      "creating INPUT1");
  std::unique_ptr<tc::InferInput> input1(input1_raw);
  FAIL_IF_ERR(
      input1->SetSharedMemory(
          "example_input_region", kTensorBytes, kTensorBytes),
      "INPUT1 shm placement");

  tc::InferRequestedOutput* output0_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0_raw, "OUTPUT0"),
      "creating OUTPUT0");
  std::unique_ptr<tc::InferRequestedOutput> output0(output0_raw);
  FAIL_IF_ERR(
      output0->SetSharedMemory("example_output_region", kTensorBytes, 0),
      "OUTPUT0 shm placement");
  tc::InferRequestedOutput* output1_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output1_raw, "OUTPUT1"),
      "creating OUTPUT1");
  std::unique_ptr<tc::InferRequestedOutput> output1(output1_raw);
  FAIL_IF_ERR(
      output1->SetSharedMemory(
          "example_output_region", kTensorBytes, kTensorBytes),
      "OUTPUT1 shm placement");

  tc::InferOptions options("simple");
  tc::InferResult* result_raw = nullptr;
  FAIL_IF_ERR(
      client->Infer(
          &result_raw, options, {input0.get(), input1.get()},
          {output0.get(), output1.get()}),
      "running inference");
  std::unique_ptr<tc::InferResult> result(result_raw);
  FAIL_IF_ERR(result->RequestStatus(), "inference response status");

  // outputs are read from the REGION; the response carried no bytes
  const int32_t* out_region = reinterpret_cast<const int32_t*>(out_addr);
  int rc = 0;
  for (int i = 0; i < 16; ++i) {
    const int32_t sum = out_region[i];
    const int32_t diff = out_region[16 + i];
    if (sum != in_region[i] + in_region[16 + i] ||
        diff != in_region[i] - in_region[16 + i]) {
      std::cerr << "error: wrong shm result at " << i << ": " << sum << ", "
                << diff << std::endl;
      rc = 1;
      break;
    }
    std::cout << in_region[i] << " + " << in_region[16 + i] << " = " << sum
              << "   " << in_region[i] << " - " << in_region[16 + i] << " = "
              << diff << std::endl;
  }

  // teardown mirrors setup exactly: unregister, unmap, unlink
  FAIL_IF_ERR(
      client->UnregisterSystemSharedMemory("example_input_region"),
      "unregistering input region");
  FAIL_IF_ERR(
      client->UnregisterSystemSharedMemory("example_output_region"),
      "unregistering output region");
  FAIL_IF_ERR(tc::UnmapSharedMemory(in_addr, kInputBytes), "unmap input");
  FAIL_IF_ERR(tc::UnmapSharedMemory(out_addr, kOutputBytes), "unmap output");
  FAIL_IF_ERR(tc::CloseSharedMemory(in_fd), "close input fd");
  FAIL_IF_ERR(tc::CloseSharedMemory(out_fd), "close output fd");
  FAIL_IF_ERR(tc::UnlinkSharedMemoryRegion(in_key), "unlink input");
  FAIL_IF_ERR(tc::UnlinkSharedMemoryRegion(out_key), "unlink output");

  if (rc == 0) {
    std::cout << "PASS : simple_grpc_shm_client" << std::endl;
  }
  return rc;
}
