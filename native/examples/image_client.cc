// Metadata-driven image classification application — the "full program"
// native example. Role parity with the reference's
// src/c++/examples/image_client.cc:60-510: interrogate the model's
// metadata to learn its input name/shape/datatype, preprocess an image
// client-side to match (resize + scaling + CHW layout), request the
// output with the classification extension, and print ranked
// "value (index) = label" lines. Where the reference links OpenCV, this
// reads binary PPM (P6) — no dependency — and synthesizes a
// deterministic test image when no file is given so the example doubles
// as a smoke test (SURVEY §4 tier 3).
//
// Build: part of the normal native build (cmake -S native -B native/build).
// Run:   image_client [-u host:port] [-m model] [-c topk]
//                     [-s NONE|INCEPTION] [image.ppm]
//        (default URL from $CLIENT_TPU_TEST_GRPC_URL, else 127.0.0.1:8001)

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"
#include "client_tpu/json.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                                  \
  do {                                                                       \
    const tc::Error err = (X);                                               \
    if (!err.IsOk()) {                                                       \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                              \
    }                                                                        \
  } while (false)

namespace {

struct Image {
  int width = 0;
  int height = 0;
  std::vector<uint8_t> rgb;  // HWC, 3 channels
};

// Binary PPM (P6) loader: header tokens (magic, width, height, maxval,
// '#' comments allowed) followed by raw RGB triplets.
bool
LoadPpm(const std::string& path, Image* img, std::string* error)
{
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    *error = "cannot open " + path;
    return false;
  }
  auto next_token = [&f]() -> std::string {
    std::string token;
    int c;
    while ((c = f.get()) != EOF) {
      if (c == '#') {  // comment to end of line
        while ((c = f.get()) != EOF && c != '\n') {
        }
        continue;
      }
      if (std::isspace(c)) {
        if (!token.empty()) {
          break;
        }
        continue;
      }
      token.push_back(static_cast<char>(c));
    }
    return token;
  };
  if (next_token() != "P6") {
    *error = path + " is not a binary PPM (P6)";
    return false;
  }
  img->width = std::atoi(next_token().c_str());
  img->height = std::atoi(next_token().c_str());
  const int maxval = std::atoi(next_token().c_str());
  if (img->width <= 0 || img->height <= 0 || maxval != 255) {
    *error = "unsupported PPM geometry/maxval in " + path;
    return false;
  }
  img->rgb.resize(static_cast<size_t>(img->width) * img->height * 3);
  f.read(reinterpret_cast<char*>(img->rgb.data()),
         static_cast<std::streamsize>(img->rgb.size()));
  if (static_cast<size_t>(f.gcount()) != img->rgb.size()) {
    *error = "truncated pixel data in " + path;
    return false;
  }
  return true;
}

// Deterministic stand-in when no image file is supplied: a smooth RGB
// gradient, so runs are reproducible and CI needs no fixture file.
Image
SyntheticImage(int width = 64, int height = 64)
{
  Image img;
  img.width = width;
  img.height = height;
  img.rgb.resize(static_cast<size_t>(width) * height * 3);
  for (int y = 0; y < height; ++y) {
    for (int x = 0; x < width; ++x) {
      uint8_t* px = &img.rgb[(static_cast<size_t>(y) * width + x) * 3];
      px[0] = static_cast<uint8_t>((x * 255) / (width - 1));
      px[1] = static_cast<uint8_t>((y * 255) / (height - 1));
      px[2] = static_cast<uint8_t>(((x + y) * 255) / (width + height - 2));
    }
  }
  return img;
}

// Nearest-neighbor resize + scaling + CHW layout, mirroring the server's
// preprocess model (client_tpu/models/vision.py ImagePreprocessModel) so
// either side of the pipeline produces the same tensor.
std::vector<float>
Preprocess(
    const Image& img, int out_h, int out_w, const std::string& scaling)
{
  std::vector<float> chw(static_cast<size_t>(3) * out_h * out_w);
  const float scale = scaling == "INCEPTION" ? 2.0f / 255.0f : 1.0f;
  const float shift = scaling == "INCEPTION" ? -1.0f : 0.0f;
  for (int y = 0; y < out_h; ++y) {
    const int src_y = y * img.height / out_h;
    for (int x = 0; x < out_w; ++x) {
      const int src_x = x * img.width / out_w;
      const uint8_t* px =
          &img.rgb[(static_cast<size_t>(src_y) * img.width + src_x) * 3];
      for (int c = 0; c < 3; ++c) {
        chw[(static_cast<size_t>(c) * out_h + y) * out_w + x] =
            px[c] * scale + shift;
      }
    }
  }
  return chw;
}

}  // namespace

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8001";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_GRPC_URL")) {
    url = env;
  }
  std::string model_name = "densenet_onnx";
  std::string scaling = "INCEPTION";
  std::string image_path;
  int topk = 3;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    } else if (std::strcmp(argv[i], "-m") == 0 && i + 1 < argc) {
      model_name = argv[++i];
    } else if (std::strcmp(argv[i], "-c") == 0 && i + 1 < argc) {
      topk = std::atoi(argv[++i]);
    } else if (std::strcmp(argv[i], "-s") == 0 && i + 1 < argc) {
      scaling = argv[++i];
    } else if (argv[i][0] != '-') {
      image_path = argv[i];
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url),
      "unable to create grpc client");

  // -- interrogate the model: everything below is driven by metadata ----
  bool model_ready = false;
  FAIL_IF_ERR(
      client->IsModelReady(&model_ready, model_name), "model readiness");
  if (!model_ready) {
    std::cerr << "error: model " << model_name << " not ready" << std::endl;
    return 1;
  }
  tc::Json metadata;
  FAIL_IF_ERR(
      client->ModelMetadata(&metadata, model_name), "model metadata");
  if (metadata.At("inputs").size() != 1 ||
      metadata.At("outputs").size() != 1) {
    std::cerr << "error: image_client expects a single-input single-output "
              << "model; " << model_name << " has "
              << metadata.At("inputs").size() << "/"
              << metadata.At("outputs").size() << std::endl;
    return 1;
  }
  const tc::Json& input_meta = metadata.At("inputs")[0];
  const tc::Json& output_meta = metadata.At("outputs")[0];
  const std::string input_name = input_meta.At("name").AsString();
  const std::string input_dtype = input_meta.At("datatype").AsString();
  const std::string output_name = output_meta.At("name").AsString();
  if (input_dtype != "FP32") {
    std::cerr << "error: expected FP32 image input, got " << input_dtype
              << std::endl;
    return 1;
  }
  std::vector<int64_t> shape;
  for (size_t i = 0; i < input_meta.At("shape").size(); ++i) {
    shape.push_back(input_meta.At("shape")[i].AsInt());
  }
  // accept CHW or HWC, with or without a leading batch dim
  std::vector<int64_t> dims = shape;
  if (dims.size() == 4) {
    dims.erase(dims.begin());
  }
  if (dims.size() != 3) {
    std::cerr << "error: unsupported input rank for image model" << std::endl;
    return 1;
  }
  const bool chw = dims[0] == 3;
  const int height = static_cast<int>(chw ? dims[1] : dims[0]);
  const int width = static_cast<int>(chw ? dims[2] : dims[1]);
  if (!chw && dims[2] != 3) {
    std::cerr << "error: input is neither CHW nor HWC" << std::endl;
    return 1;
  }

  // -- load + preprocess ------------------------------------------------
  Image img;
  if (image_path.empty()) {
    img = SyntheticImage();
    std::cout << "no image file given; using synthetic "
              << img.width << "x" << img.height << " gradient" << std::endl;
  } else {
    std::string error;
    if (!LoadPpm(image_path, &img, &error)) {
      std::cerr << "error: " << error << std::endl;
      return 1;
    }
  }
  std::vector<float> pixels = Preprocess(img, height, width, scaling);
  if (!chw) {
    // transpose CHW -> HWC for HWC models
    std::vector<float> hwc(pixels.size());
    for (int c = 0; c < 3; ++c) {
      for (int y = 0; y < height; ++y) {
        for (int x = 0; x < width; ++x) {
          hwc[(static_cast<size_t>(y) * width + x) * 3 + c] =
              pixels[(static_cast<size_t>(c) * height + y) * width + x];
        }
      }
    }
    pixels.swap(hwc);
  }

  // -- infer with the classification extension --------------------------
  tc::InferInput* input_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input_raw, input_name, shape, "FP32"),
      "creating input");
  std::unique_ptr<tc::InferInput> input(input_raw);
  FAIL_IF_ERR(
      input->AppendRaw(
          reinterpret_cast<const uint8_t*>(pixels.data()),
          pixels.size() * sizeof(float)),
      "setting input data");

  tc::InferRequestedOutput* output_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(
          &output_raw, output_name, static_cast<size_t>(topk)),
      "creating requested output");
  std::unique_ptr<tc::InferRequestedOutput> output(output_raw);

  tc::InferOptions options(model_name);
  options.request_id = "image-1";
  tc::InferResult* result_raw = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result_raw, options, {input.get()}, {output.get()}),
      "running inference");
  std::unique_ptr<tc::InferResult> result(result_raw);
  FAIL_IF_ERR(result->RequestStatus(), "inference response status");

  // classification responses are BYTES "value:index[:label]" strings
  std::vector<std::string> classes;
  FAIL_IF_ERR(result->StringData(output_name, &classes), "classification");
  if (classes.size() != static_cast<size_t>(topk)) {
    std::cerr << "error: asked for top-" << topk << ", got "
              << classes.size() << std::endl;
    return 1;
  }
  std::cout << "Image '" << (image_path.empty() ? "<synthetic>" : image_path)
            << "':" << std::endl;
  double prev_value = 0.0;
  for (size_t i = 0; i < classes.size(); ++i) {
    const std::string& entry = classes[i];
    const size_t first = entry.find(':');
    const size_t second =
        first == std::string::npos ? std::string::npos
                                   : entry.find(':', first + 1);
    if (first == std::string::npos) {
      std::cerr << "error: malformed classification entry '" << entry << "'"
                << std::endl;
      return 1;
    }
    const std::string value_str = entry.substr(0, first);
    const std::string index_str = entry.substr(
        first + 1,
        second == std::string::npos ? std::string::npos : second - first - 1);
    const std::string label =
        second == std::string::npos ? "" : entry.substr(second + 1);
    const double value = std::atof(value_str.c_str());
    if (i > 0 && value > prev_value) {
      std::cerr << "error: classification not ranked: " << value << " after "
                << prev_value << std::endl;
      return 1;
    }
    prev_value = value;
    std::cout << "    " << value_str << " (" << index_str << ")"
              << (label.empty() ? "" : " = " + label) << std::endl;
  }

  std::cout << "PASS : image_client" << std::endl;
  return 0;
}
