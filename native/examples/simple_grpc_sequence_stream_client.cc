// Stateful sequences over bi-di GRPC streaming — the native counterpart of
// examples/simple_grpc_sequence_stream_infer_client.py. Role parity with
// the reference's src/c++/examples/simple_grpc_sequence_stream_infer_client.cc:
// two interleaved sequences share one stream, each carrying
// sequence_id/start/end controls; the server accumulates per-sequence state
// and the client verifies the running sums arrive per-sequence in order.
//
// Build: part of the normal native build (cmake -S native -B native/build).
// Run:   simple_grpc_sequence_stream_client [-u host:port] [-n steps]
//        (default URL from $CLIENT_TPU_TEST_GRPC_URL, else 127.0.0.1:8001)

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                                  \
  do {                                                                       \
    const tc::Error err = (X);                                               \
    if (!err.IsOk()) {                                                       \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                              \
    }                                                                        \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8001";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_GRPC_URL")) {
    url = env;
  }
  int steps = 4;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    } else if (std::strcmp(argv[i], "-n") == 0 && i + 1 < argc) {
      steps = std::atoi(argv[++i]);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url),
      "unable to create grpc client");

  // responses from both sequences arrive on one reader thread; bucket the
  // running sums by the request id prefix we set per sequence
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<int32_t>> sums;
  int expected = 2 * steps;
  int received = 0;
  std::string stream_error;

  FAIL_IF_ERR(
      client->StartStream([&](tc::InferResult* result, const tc::Error& err) {
        std::unique_ptr<tc::InferResult> owned(result);
        std::lock_guard<std::mutex> lock(mu);
        if (err) {
          stream_error = err.Message();
          cv.notify_one();
          return;
        }
        std::string id;
        const uint8_t* buf = nullptr;
        size_t nbytes = 0;
        if (owned != nullptr && !owned->Id(&id) &&
            !owned->RawData("OUTPUT", &buf, &nbytes) &&
            nbytes == sizeof(int32_t)) {
          int32_t value;
          std::memcpy(&value, buf, sizeof(value));
          sums[id.substr(0, id.find('-'))].push_back(value);
          if (++received == expected) {
            cv.notify_one();
          }
        }
      }),
      "starting stream");

  // sequence A adds +5 per step, sequence B adds +7; both interleave on
  // the SAME stream and the server keeps their accumulators separate
  struct Seq {
    const char* tag;
    uint64_t id;
    int32_t increment;
  };
  const Seq sequences[] = {{"A", 1001, 5}, {"B", 1002, 7}};
  std::vector<std::unique_ptr<tc::InferInput>> keepalive;
  for (int step = 0; step < steps; ++step) {
    for (const Seq& seq : sequences) {
      tc::InferInput* raw = nullptr;
      FAIL_IF_ERR(
          tc::InferInput::Create(&raw, "INPUT", {1, 1}, "INT32"),
          "creating INPUT");
      std::unique_ptr<tc::InferInput> input(raw);
      FAIL_IF_ERR(
          input->AppendRaw(
              reinterpret_cast<const uint8_t*>(&seq.increment),
              sizeof(seq.increment)),
          "setting INPUT");
      tc::InferOptions options("simple_sequence");
      options.sequence_id = seq.id;
      options.sequence_start = (step == 0);
      options.sequence_end = (step == steps - 1);
      options.request_id =
          std::string(seq.tag) + "-" + std::to_string(step);
      FAIL_IF_ERR(
          client->AsyncStreamInfer(options, {input.get()}),
          "stream infer");
      keepalive.push_back(std::move(input));  // alive until responses land
    }
  }

  {
    std::unique_lock<std::mutex> lock(mu);
    if (!cv.wait_for(lock, std::chrono::seconds(60), [&] {
          return received == expected || !stream_error.empty();
        })) {
      std::cerr << "error: timed out at " << received << "/" << expected
                << " responses" << std::endl;
      return 1;
    }
  }
  FAIL_IF_ERR(client->StopStream(), "stopping stream");
  if (!stream_error.empty()) {
    std::cerr << "error: stream: " << stream_error << std::endl;
    return 1;
  }

  for (const Seq& seq : sequences) {
    const std::vector<int32_t>& got = sums[seq.tag];
    if (static_cast<int>(got.size()) != steps) {
      std::cerr << "error: sequence " << seq.tag << " got " << got.size()
                << "/" << steps << " responses" << std::endl;
      return 1;
    }
    std::cout << "sequence " << seq.tag << " (+" << seq.increment << "):";
    for (int step = 0; step < steps; ++step) {
      const int32_t want = seq.increment * (step + 1);
      if (got[step] != want) {
        std::cerr << "error: " << seq.tag << " step " << step << " = "
                  << got[step] << ", want " << want << std::endl;
        return 1;
      }
      std::cout << " " << got[step];
    }
    std::cout << std::endl;
  }

  std::cout << "PASS : simple_grpc_sequence_stream_client" << std::endl;
  return 0;
}
