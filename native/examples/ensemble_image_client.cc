// Server-side pipeline (ensemble) classification — the native counterpart
// of examples/ensemble_image_client.py. Role parity with the reference's
// src/c++/examples/ensemble_image_client.cc: the client sends the RAW
// UINT8 HWC image to the `ensemble_image` model and the server runs the
// whole pipeline (preprocess -> densenet_onnx) internally; the
// classification extension returns ranked "value:index:label" strings.
// Contrast with image_client.cc, which does the preprocessing client-side.
//
// Build: part of the normal native build (cmake -S native -B native/build).
// Run:   ensemble_image_client [-u host:port] [-c topk] [image.ppm]
//        (default URL from $CLIENT_TPU_TEST_GRPC_URL, else 127.0.0.1:8001)

#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/grpc_client.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                                  \
  do {                                                                       \
    const tc::Error err = (X);                                               \
    if (!err.IsOk()) {                                                       \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                              \
    }                                                                        \
  } while (false)

namespace {

// Binary PPM (P6) loader (same minimal format image_client.cc reads).
bool
LoadPpm(
    const std::string& path, int* width, int* height,
    std::vector<uint8_t>* rgb, std::string* error)
{
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    *error = "cannot open " + path;
    return false;
  }
  auto next_token = [&f]() -> std::string {
    std::string token;
    int c;
    while ((c = f.get()) != EOF) {
      if (c == '#') {
        while ((c = f.get()) != EOF && c != '\n') {
        }
        continue;
      }
      if (std::isspace(c)) {
        if (!token.empty()) {
          break;
        }
        continue;
      }
      token.push_back(static_cast<char>(c));
    }
    return token;
  };
  if (next_token() != "P6") {
    *error = path + " is not a binary PPM (P6)";
    return false;
  }
  *width = std::atoi(next_token().c_str());
  *height = std::atoi(next_token().c_str());
  const int maxval = std::atoi(next_token().c_str());
  if (*width <= 0 || *height <= 0 || maxval != 255) {
    *error = "unsupported PPM geometry/maxval in " + path;
    return false;
  }
  rgb->resize(static_cast<size_t>(*width) * *height * 3);
  f.read(reinterpret_cast<char*>(rgb->data()),
         static_cast<std::streamsize>(rgb->size()));
  if (static_cast<size_t>(f.gcount()) != rgb->size()) {
    *error = "truncated pixel data in " + path;
    return false;
  }
  return true;
}

}  // namespace

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8001";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_GRPC_URL")) {
    url = env;
  }
  std::string image_path;
  int topk = 3;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    } else if (std::strcmp(argv[i], "-c") == 0 && i + 1 < argc) {
      topk = std::atoi(argv[++i]);
    } else if (argv[i][0] != '-') {
      image_path = argv[i];
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url),
      "unable to create grpc client");

  bool model_ready = false;
  FAIL_IF_ERR(
      client->IsModelReady(&model_ready, "ensemble_image"),
      "model readiness");
  if (!model_ready) {
    std::cerr << "error: ensemble_image not ready (server must register "
              << "the image ensemble pipeline)" << std::endl;
    return 1;
  }

  // the ensemble takes the raw image: no client-side preprocessing at all
  int width = 64;
  int height = 64;
  std::vector<uint8_t> rgb;
  if (image_path.empty()) {
    rgb.resize(static_cast<size_t>(width) * height * 3);
    for (int y = 0; y < height; ++y) {
      for (int x = 0; x < width; ++x) {
        uint8_t* px = &rgb[(static_cast<size_t>(y) * width + x) * 3];
        px[0] = static_cast<uint8_t>((x * 255) / (width - 1));
        px[1] = static_cast<uint8_t>((y * 255) / (height - 1));
        px[2] = static_cast<uint8_t>(((x + y) * 255) / (width + height - 2));
      }
    }
    std::cout << "no image file given; using synthetic " << width << "x"
              << height << " gradient" << std::endl;
  } else {
    std::string error;
    if (!LoadPpm(image_path, &width, &height, &rgb, &error)) {
      std::cerr << "error: " << error << std::endl;
      return 1;
    }
  }

  tc::InferInput* input_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(
          &input_raw, "IMAGE", {height, width, 3}, "UINT8"),
      "creating IMAGE");
  std::unique_ptr<tc::InferInput> input(input_raw);
  FAIL_IF_ERR(input->AppendRaw(rgb.data(), rgb.size()), "setting IMAGE");

  tc::InferRequestedOutput* output_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(
          &output_raw, "CLASSIFICATION", static_cast<size_t>(topk)),
      "creating requested output");
  std::unique_ptr<tc::InferRequestedOutput> output(output_raw);

  tc::InferOptions options("ensemble_image");
  tc::InferResult* result_raw = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result_raw, options, {input.get()}, {output.get()}),
      "running ensemble inference");
  std::unique_ptr<tc::InferResult> result(result_raw);
  FAIL_IF_ERR(result->RequestStatus(), "ensemble response status");

  std::vector<std::string> classes;
  FAIL_IF_ERR(
      result->StringData("CLASSIFICATION", &classes), "classification");
  if (classes.size() != static_cast<size_t>(topk)) {
    std::cerr << "error: asked for top-" << topk << ", got "
              << classes.size() << std::endl;
    return 1;
  }
  std::cout << "Top " << topk
            << " classes (server-side preprocess + classify):" << std::endl;
  for (const std::string& entry : classes) {
    const size_t first = entry.find(':');
    if (first == std::string::npos) {
      std::cerr << "error: malformed entry '" << entry << "'" << std::endl;
      return 1;
    }
    const size_t second = entry.find(':', first + 1);
    std::cout << "    " << entry.substr(0, first) << " ("
              << entry.substr(
                     first + 1,
                     second == std::string::npos ? std::string::npos
                                                 : second - first - 1)
              << ")"
              << (second == std::string::npos
                      ? ""
                      : " = " + entry.substr(second + 1))
              << std::endl;
  }

  std::cout << "PASS : ensemble_image_client" << std::endl;
  return 0;
}
