// Basic sync HTTP inference against the "simple" model — the libcurl
// client twin of simple_grpc_infer_client.cc. Role parity with the
// reference's src/c++/examples/simple_http_infer_client.cc: health checks,
// model metadata, two INT32[1,16] inputs, sum/diff outputs verified element
// by element, nonzero exit on any mismatch (examples double as smoke
// tests, SURVEY §4 tier 3).
//
// Build: part of the normal native build (cmake -S native -B native/build).
// Run:   simple_http_infer_client [-u host:port] [-v]
//        (default URL from $CLIENT_TPU_TEST_URL, else 127.0.0.1:8000)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "client_tpu/common.h"
#include "client_tpu/http_client.h"

namespace tc = client_tpu;

#define FAIL_IF_ERR(X, MSG)                                                  \
  do {                                                                       \
    const tc::Error err = (X);                                               \
    if (!err.IsOk()) {                                                       \
      std::cerr << "error: " << (MSG) << ": " << err.Message() << std::endl; \
      return 1;                                                              \
    }                                                                        \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "127.0.0.1:8000";
  if (const char* env = std::getenv("CLIENT_TPU_TEST_URL")) {
    url = env;
  }
  bool verbose = false;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-u") == 0 && i + 1 < argc) {
      url = argv[++i];
    } else if (std::strcmp(argv[i], "-v") == 0) {
      verbose = true;
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose),
      "unable to create http client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server liveness");
  bool ready = false;
  FAIL_IF_ERR(client->IsServerReady(&ready), "server readiness");
  if (!live || !ready) {
    std::cerr << "error: server not live/ready" << std::endl;
    return 1;
  }
  tc::Json metadata;
  FAIL_IF_ERR(client->ModelMetadata(&metadata, "simple"), "model metadata");

  std::vector<int32_t> input0_data(16);
  std::vector<int32_t> input1_data(16);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
    input1_data[i] = 1;
  }
  std::vector<int64_t> shape{1, 16};

  tc::InferInput* input0_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0_raw, "INPUT0", shape, "INT32"),
      "creating INPUT0");
  std::unique_ptr<tc::InferInput> input0(input0_raw);
  FAIL_IF_ERR(
      input0->AppendRaw(
          reinterpret_cast<const uint8_t*>(input0_data.data()),
          input0_data.size() * sizeof(int32_t)),
      "setting INPUT0 data");

  tc::InferInput* input1_raw = nullptr;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1_raw, "INPUT1", shape, "INT32"),
      "creating INPUT1");
  std::unique_ptr<tc::InferInput> input1(input1_raw);
  FAIL_IF_ERR(
      input1->AppendRaw(
          reinterpret_cast<const uint8_t*>(input1_data.data()),
          input1_data.size() * sizeof(int32_t)),
      "setting INPUT1 data");

  tc::InferOptions options("simple");
  options.request_id = "http-1";

  tc::InferResult* result_raw = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result_raw, options, {input0.get(), input1.get()}),
      "running inference");
  std::unique_ptr<tc::InferResult> result(result_raw);
  FAIL_IF_ERR(result->RequestStatus(), "inference response status");

  const uint8_t* out0_buf = nullptr;
  size_t out0_size = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &out0_buf, &out0_size), "OUTPUT0");
  const uint8_t* out1_buf = nullptr;
  size_t out1_size = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT1", &out1_buf, &out1_size), "OUTPUT1");
  if (out0_size != 16 * sizeof(int32_t) || out1_size != 16 * sizeof(int32_t)) {
    std::cerr << "error: unexpected output sizes " << out0_size << "/"
              << out1_size << std::endl;
    return 1;
  }

  const int32_t* sums = reinterpret_cast<const int32_t*>(out0_buf);
  const int32_t* diffs = reinterpret_cast<const int32_t*>(out1_buf);
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != input0_data[i] + input1_data[i] ||
        diffs[i] != input0_data[i] - input1_data[i]) {
      std::cerr << "error: wrong result at " << i << ": " << sums[i] << ", "
                << diffs[i] << std::endl;
      return 1;
    }
    std::cout << input0_data[i] << " + " << input1_data[i] << " = " << sums[i]
              << "   " << input0_data[i] << " - " << input1_data[i] << " = "
              << diffs[i] << std::endl;
  }

  std::cout << "PASS : simple_http_infer_client" << std::endl;
  return 0;
}
