#!/usr/bin/env python
"""LLM generation over the HTTP generate extension (SSE streaming).

The HTTP counterpart of llm_generate_stream_client.py: instead of a GRPC
bi-di stream, the request is one flat JSON POST to
``/v2/models/{m}/generate_stream`` and tokens arrive as Server-Sent
Events — tritonserver's extension_generate shape, the endpoint genai-perf
benchmarks. Also demonstrates the one-shot ``/generate`` route.
See docs/generate_extension.md for the protocol mapping.
"""

import argparse
import sys
import time

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-p", "--prompt", default="10,20,30,40",
                        help="comma-separated prompt token ids (0-255)")
    parser.add_argument("-n", "--max-tokens", type=int, default=16)
    args = parser.parse_args()

    prompt = [[int(t) for t in args.prompt.split(",")]]
    with httpclient.InferenceServerClient(args.url) as client:
        # streaming: one SSE event per generated token, consumed live
        start = time.perf_counter()
        first_ms = None
        tokens = []
        for event in client.generate_stream(
            "tiny_lm_generate",
            {"TOKENS": prompt, "MAX_TOKENS": args.max_tokens},
        ):
            if first_ms is None:
                first_ms = (time.perf_counter() - start) * 1e3
            tokens.append(event["NEXT_TOKEN"])
            print(f"token[{event['INDEX']}] = {event['NEXT_TOKEN']}")
        total_ms = (time.perf_counter() - start) * 1e3

        if len(tokens) != args.max_tokens:
            print(f"error: expected {args.max_tokens} tokens, "
                  f"got {len(tokens)}")
            return 1
        print(f"generated {len(tokens)} tokens: ttft {first_ms:.1f} ms, "
              f"total {total_ms:.1f} ms")

        # one-shot: a single-response generation comes back as one JSON
        one = client.generate(
            "tiny_lm_generate", {"TOKENS": prompt, "MAX_TOKENS": 1})
        if one["NEXT_TOKEN"] != tokens[0]:
            print(f"error: one-shot token {one['NEXT_TOKEN']} != "
                  f"streamed first token {tokens[0]} (greedy must agree)")
            return 1
        print(f"one-shot /generate agrees: {one['NEXT_TOKEN']}")
        print("PASS: llm_http_generate_client")
        return 0


if __name__ == "__main__":
    sys.exit(main())
