#!/usr/bin/env python
"""Callback-style async HTTP inference (thread-pool futures).

Equivalent of the reference's simple_http_async_infer_client.py.
"""

import argparse
import sys

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-c", "--concurrency", type=int, default=4)
    args = parser.parse_args()

    request_count = 8
    with httpclient.InferenceServerClient(args.url, concurrency=args.concurrency) as client:
        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.ones((1, 16), dtype=np.int32)
        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0_data)
        inputs[1].set_data_from_numpy(input1_data)

        handles = [client.async_infer("simple", inputs) for _ in range(request_count)]
        for handle in handles:
            result = handle.get_result()
            if not (result.as_numpy("OUTPUT0") == input0_data + input1_data).all():
                sys.exit("async infer error: incorrect sum")
        print(f"PASS: {request_count} async requests")


if __name__ == "__main__":
    main()
