#!/usr/bin/env python
"""Stateful sequences over plain sync HTTP infer (no stream).

Equivalent of the reference's simple_http_sequence_sync_infer_client.py:
per-request sequence_id + start/end flags carried in request parameters.
"""

import argparse
import sys

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    values = [4, 3, 2, 1]
    with httpclient.InferenceServerClient(args.url) as client:
        totals = {}
        for seq_id in (2001, 2002):
            total = 0
            for i, v in enumerate(values):
                inp = httpclient.InferInput("INPUT", [1, 1], "INT32")
                inp.set_data_from_numpy(np.array([[v]], dtype=np.int32))
                result = client.infer(
                    "simple_sequence",
                    [inp],
                    sequence_id=seq_id,
                    sequence_start=(i == 0),
                    sequence_end=(i == len(values) - 1),
                )
                total = int(result.as_numpy("OUTPUT")[0, 0])
            totals[seq_id] = total
    if totals != {2001: sum(values), 2002: sum(values)}:
        sys.exit(f"sequence sync error: {totals}")
    print(f"PASS: sequence sync (totals {totals})")


if __name__ == "__main__":
    main()
