#!/usr/bin/env python
"""Health + metadata surface walkthrough (equivalent of
simple_http_health_metadata.py)."""

import argparse
import sys

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        if not client.is_server_live():
            sys.exit("FAILED: server not live")
        if not client.is_server_ready():
            sys.exit("FAILED: server not ready")
        if not client.is_model_ready("simple"):
            sys.exit("FAILED: model 'simple' not ready")
        server_md = client.get_server_metadata()
        print("server:", server_md["name"], server_md["version"])
        print("extensions:", ", ".join(server_md["extensions"]))
        model_md = client.get_model_metadata("simple")
        print("model inputs:", [t["name"] for t in model_md["inputs"]])
        config = client.get_model_config("simple")
        print("backend:", config["backend"])
        stats = client.get_inference_statistics("simple")
        print("stats:", stats["model_stats"][0]["inference_count"], "inferences")
        print("PASS: health/metadata")


if __name__ == "__main__":
    main()
