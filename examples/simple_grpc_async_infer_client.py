#!/usr/bin/env python
"""Callback-style async GRPC inference.

Equivalent of the reference's simple_grpc_async_infer_client.py.
"""

import argparse
import queue
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    request_count = 8
    results = queue.Queue()
    with grpcclient.InferenceServerClient(args.url) as client:
        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.ones((1, 16), dtype=np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0_data)
        inputs[1].set_data_from_numpy(input1_data)

        for _ in range(request_count):
            client.async_infer(
                "simple", inputs, callback=lambda r, e: results.put((r, e))
            )
        for _ in range(request_count):
            result, error = results.get(timeout=30)
            if error is not None:
                sys.exit(f"async infer error: {error}")
            if not (result.as_numpy("OUTPUT1") == input0_data - input1_data).all():
                sys.exit("async infer error: incorrect difference")
        print(f"PASS: {request_count} async requests")


if __name__ == "__main__":
    main()
