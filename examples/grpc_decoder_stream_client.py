#!/usr/bin/env python
"""LLM-style autoregressive decode loop over the bidi stream.

Drives the ``decoder_lm`` model (KV cache in server-side sequence state)
exactly how an LLM serving client works: send the prompt with
sequence_start, then feed each greedy NEXT_TOKEN back one request at a
time on the same sequence_id, and close with sequence_end.

This is the workload the reference's sequence extension exists for —
simple_grpc_sequence_stream_infer_client.py demonstrates the protocol
with an accumulator; this demonstrates it with a real transformer decode.
"""

import argparse
import queue

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("--prompt", default="42,17,99",
                        help="comma-separated token ids (< 256)")
    parser.add_argument("-n", "--new-tokens", type=int, default=16)
    args = parser.parse_args()

    prompt = [int(t) % 256 for t in args.prompt.split(",")]
    results = queue.Queue()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.start_stream(callback=lambda r, e: results.put((r, e)))

        inp = grpcclient.InferInput("TOKENS", [1, len(prompt)], "INT32")
        inp.set_data_from_numpy(np.asarray(prompt, np.int32).reshape(1, -1))
        client.async_stream_infer(
            "decoder_lm", [inp], sequence_id=1, sequence_start=True)

        generated = []
        for i in range(args.new_tokens):
            result, error = results.get(timeout=60)
            if error is not None:
                raise SystemExit(f"stream error at step {i}: {error}")
            token = int(result.as_numpy("NEXT_TOKEN")[0, 0])
            generated.append(token)
            last = i == args.new_tokens - 1
            inp = grpcclient.InferInput("TOKENS", [1, 1], "INT32")
            inp.set_data_from_numpy(np.array([[token]], np.int32))
            client.async_stream_infer(
                "decoder_lm", [inp], sequence_id=1, sequence_end=last)
        results.get(timeout=60)  # the sequence_end response
        client.stop_stream()

    print(f"prompt:    {prompt}")
    print(f"generated: {generated}")
    print("PASS" if len(generated) == args.new_tokens else "FAIL")


if __name__ == "__main__":
    main()
