// Dependency-free KServe v2 HTTP client for Node >= 18 (built-in fetch).
//
// No npm install needed — this is the REST analog of client.js for users
// who can't take grpc dependencies. Exercises /v2 health + metadata and a
// binary-tensor infer with the Inference-Header-Content-Length framing
// (the same body layout client_tpu.http builds).
//
//   node http_client.js [host:port]    (default localhost:8000)
"use strict";

const base = `http://${process.argv[2] || "localhost:8000"}`;

function int32sToLE(values) {
  const buf = Buffer.alloc(4 * values.length);
  values.forEach((v, i) => buf.writeInt32LE(v, 4 * i));
  return buf;
}

function leToInt32s(buf) {
  const out = [];
  for (let i = 0; i + 4 <= buf.length; i += 4) out.push(buf.readInt32LE(i));
  return out;
}

async function main() {
  const live = await fetch(`${base}/v2/health/live`);
  console.log("server live:", live.ok);
  const meta = await (await fetch(`${base}/v2/models/simple`)).json();
  console.log("model:", meta.name, "inputs:", meta.inputs.length);

  const input0 = Array.from({ length: 16 }, (_, i) => i);
  const input1 = Array.from({ length: 16 }, () => 1);
  const raw0 = int32sToLE(input0);
  const raw1 = int32sToLE(input1);
  const header = Buffer.from(JSON.stringify({
    inputs: [
      { name: "INPUT0", datatype: "INT32", shape: [1, 16],
        parameters: { binary_data_size: raw0.length } },
      { name: "INPUT1", datatype: "INT32", shape: [1, 16],
        parameters: { binary_data_size: raw1.length } },
    ],
    outputs: [
      { name: "OUTPUT0", parameters: { binary_data: true } },
      { name: "OUTPUT1", parameters: { binary_data: true } },
    ],
  }));

  const resp = await fetch(`${base}/v2/models/simple/infer`, {
    method: "POST",
    headers: {
      "Content-Type": "application/octet-stream",
      "Inference-Header-Content-Length": String(header.length),
    },
    body: Buffer.concat([header, raw0, raw1]),
  });
  if (!resp.ok) throw new Error(`infer failed: ${resp.status}`);

  const body = Buffer.from(await resp.arrayBuffer());
  const jsonLen = Number(resp.headers.get("inference-header-content-length"));
  const reply = JSON.parse(body.subarray(0, jsonLen).toString());
  let offset = jsonLen;
  const outputs = {};
  for (const out of reply.outputs) {
    const size = out.parameters.binary_data_size;
    outputs[out.name] = leToInt32s(body.subarray(offset, offset + size));
    offset += size;
  }
  for (let i = 0; i < 16; i += 1) {
    if (outputs.OUTPUT0[i] !== input0[i] + input1[i] ||
        outputs.OUTPUT1[i] !== input0[i] - input1[i]) {
      throw new Error(`mismatch at ${i}`);
    }
  }
  console.log("PASS: sum/diff verified for all 16 elements");
}

main().catch((e) => { console.error(e); process.exit(1); });
