// JS generated-stub example for inference.GRPCInferenceService.
//
// Parity with the reference's src/grpc_generated/javascript/client.js
// (:28-53 — @grpc/proto-loader dynamic load + simple infer), written fresh
// against this repo's vendored proto/grpc_service.proto.
//
//   npm install @grpc/grpc-js @grpc/proto-loader
//   node client.js [host:port]    (default localhost:8001)
//
// The "simple" model takes two INT32[1,16] tensors and returns their
// elementwise sum (OUTPUT0) and difference (OUTPUT1).
"use strict";

const path = require("path");
const grpc = require("@grpc/grpc-js");
const protoLoader = require("@grpc/proto-loader");

const PROTO = path.join(__dirname, "..", "..", "proto", "grpc_service.proto");
const url = process.argv[2] || "localhost:8001";

const definition = protoLoader.loadSync(PROTO, {
  keepCase: true,
  longs: Number,
  enums: String,
  defaults: true,
});
const inference = grpc.loadPackageDefinition(definition).inference;
const client = new inference.GRPCInferenceService(
  url, grpc.credentials.createInsecure());

function int32sToLE(values) {
  const buf = Buffer.alloc(4 * values.length);
  values.forEach((v, i) => buf.writeInt32LE(v, 4 * i));
  return buf;
}

function leToInt32s(buf) {
  const out = [];
  for (let i = 0; i + 4 <= buf.length; i += 4) out.push(buf.readInt32LE(i));
  return out;
}

const input0 = Array.from({ length: 16 }, (_, i) => i);
const input1 = Array.from({ length: 16 }, () => 1);

client.ServerLive({}, (err, live) => {
  if (err) throw err;
  console.log("server live:", live.live);
  const request = {
    model_name: "simple",
    inputs: [
      { name: "INPUT0", datatype: "INT32", shape: [1, 16] },
      { name: "INPUT1", datatype: "INT32", shape: [1, 16] },
    ],
    outputs: [{ name: "OUTPUT0" }, { name: "OUTPUT1" }],
    raw_input_contents: [int32sToLE(input0), int32sToLE(input1)],
  };
  client.ModelInfer(request, (inferErr, response) => {
    if (inferErr) throw inferErr;
    const sum = leToInt32s(response.raw_output_contents[0]);
    const diff = leToInt32s(response.raw_output_contents[1]);
    for (let i = 0; i < 16; i += 1) {
      if (sum[i] !== input0[i] + input1[i] ||
          diff[i] !== input0[i] - input1[i]) {
        throw new Error(`mismatch at ${i}: sum=${sum[i]} diff=${diff[i]}`);
      }
    }
    console.log("PASS: sum/diff verified for all 16 elements");
    client.close();
  });
});
