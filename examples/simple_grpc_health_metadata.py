#!/usr/bin/env python
"""Health + metadata over GRPC (equivalent of simple_grpc_health_metadata.py)."""

import argparse
import sys

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        if not (client.is_server_live() and client.is_server_ready()):
            sys.exit("FAILED: server not live/ready")
        if not client.is_model_ready("simple"):
            sys.exit("FAILED: model not ready")
        md = client.get_server_metadata()
        print("server:", md.get("name"), md.get("version"))
        model_md = client.get_model_metadata("simple")
        print("model inputs:", [t["name"] for t in model_md["inputs"]])
        cfg = client.get_model_config("simple")["config"]
        print("backend:", cfg["backend"])
        stats = client.get_inference_statistics("simple")
        print("executions:", stats["model_stats"][0].get("execution_count", 0))
        print("PASS: grpc health/metadata")


if __name__ == "__main__":
    main()
