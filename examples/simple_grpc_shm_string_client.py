#!/usr/bin/env python
"""BYTES tensors through shared memory over GRPC.

Equivalent of the reference's simple_grpc_shm_string_client.py: string
tensors serialized into a system shm region (4-byte-LE length prefixes),
outputs read back from a region with the response's reported byte size.
"""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.utils.shared_memory as shm
from client_tpu.utils import serialized_byte_size


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.unregister_system_shared_memory()

        in0 = np.array([[str(i) for i in range(16)]], dtype=np.object_)
        in1 = np.array([["10"] * 16], dtype=np.object_)
        in0_size = serialized_byte_size(in0)
        in1_size = serialized_byte_size(in1)
        out_capacity = 4 * (in0_size + in1_size)

        shm_ip = shm.create_shared_memory_region(
            "input_data", "/str_shm_in", in0_size + in1_size
        )
        shm.set_shared_memory_region(shm_ip, [in0])
        shm.set_shared_memory_region(shm_ip, [in1], offset=in0_size)
        client.register_system_shared_memory(
            "input_data", "/str_shm_in", in0_size + in1_size
        )
        shm_op = shm.create_shared_memory_region(
            "output_data", "/str_shm_out", out_capacity
        )
        client.register_system_shared_memory("output_data", "/str_shm_out", out_capacity)

        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "BYTES"),
            grpcclient.InferInput("INPUT1", [1, 16], "BYTES"),
        ]
        inputs[0].set_shared_memory("input_data", in0_size)
        inputs[1].set_shared_memory("input_data", in1_size, offset=in0_size)
        outputs = [grpcclient.InferRequestedOutput("OUTPUT0")]
        outputs[0].set_shared_memory("output_data", out_capacity)

        result = client.infer("simple_string", inputs, outputs=outputs)
        # the response reports how many bytes the output actually used
        out_meta = result.get_output("OUTPUT0")
        used = out_meta["parameters"]["shared_memory_byte_size"]["int64_param"]
        sums = shm.get_contents_as_numpy(shm_op, "BYTES", [1, 16])
        ok = all(int(sums[0][i]) == i + 10 for i in range(16)) and used > 0

        client.unregister_system_shared_memory()
        shm.destroy_shared_memory_region(shm_ip)
        shm.destroy_shared_memory_region(shm_op)
        if not ok:
            sys.exit("shm string error: incorrect results")
        print("PASS: grpc shm string")


if __name__ == "__main__":
    main()
