#!/usr/bin/env python
"""asyncio HTTP inference (equivalent of simple_http_aio_infer_client.py)."""

import argparse
import asyncio
import sys

import numpy as np

import client_tpu.http.aio as httpclient


async def run(url):
    async with httpclient.InferenceServerClient(url) as client:
        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.ones((1, 16), dtype=np.int32)
        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0_data)
        inputs[1].set_data_from_numpy(input1_data)
        results = await asyncio.gather(
            *[client.infer("simple", inputs) for _ in range(4)]
        )
        for result in results:
            if not (result.as_numpy("OUTPUT0") == input0_data + input1_data).all():
                sys.exit("aio infer error: incorrect sum")
        print("PASS: aio infer")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()
    asyncio.run(run(args.url))


if __name__ == "__main__":
    main()
