#!/usr/bin/env python
"""Stateful sequences over the bidi stream.

Equivalent of the reference's simple_grpc_sequence_stream_infer_client.py
(:59-81): two interleaved sequences, per-request sequence_id + start/end
flags, responses correlated through the stream callback.
"""

import argparse
import queue
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    values = [11, 7, 5, 3, 2, 0, 1]
    results = queue.Queue()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.start_stream(callback=lambda r, e: results.put((r, e)))
        # two interleaved sequences: one accumulates +v, one -v (via sign)
        for seq_id, sign in ((1001, 1), (1002, -1)):
            for i, v in enumerate(values):
                inp = grpcclient.InferInput("INPUT", [1, 1], "INT32")
                inp.set_data_from_numpy(np.array([[sign * v]], dtype=np.int32))
                client.async_stream_infer(
                    "simple_sequence",
                    [inp],
                    sequence_id=seq_id,
                    sequence_start=(i == 0),
                    sequence_end=(i == len(values) - 1),
                )
        received = []
        for _ in range(2 * len(values)):
            result, error = results.get(timeout=30)
            if error is not None:
                sys.exit(f"stream error: {error}")
            received.append(int(result.as_numpy("OUTPUT")[0, 0]))
        client.stop_stream()

    expected = sum(values)
    # responses arrive in request order: seq 1001's partials then seq 1002's
    if received[len(values) - 1] != expected or received[-1] != -expected:
        sys.exit(f"sequence error: totals {received[len(values)-1]}, {received[-1]}")
    print(f"PASS: sequence streaming (totals +/-{expected})")


if __name__ == "__main__":
    main()
