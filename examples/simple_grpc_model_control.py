#!/usr/bin/env python
"""Model repository control over GRPC (equivalent of simple_grpc_model_control.py)."""

import argparse
import sys

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        index = client.get_model_repository_index()
        print("repository:", [(m["name"], m.get("state", "")) for m in index])
        client.unload_model("simple_string")
        if client.is_model_ready("simple_string"):
            sys.exit("FAILED: still ready after unload")
        client.load_model("simple_string")
        if not client.is_model_ready("simple_string"):
            sys.exit("FAILED: not ready after load")
        print("PASS: grpc model control")


if __name__ == "__main__":
    main()
