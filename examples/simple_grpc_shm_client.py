#!/usr/bin/env python
"""System shared-memory inference over GRPC: the full zero-copy lifecycle.

Equivalent of the reference's simple_grpc_shm_client.py:90-183 —
create -> register -> set -> infer(shm in/out) -> read from region ->
unregister -> destroy.
"""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.utils.shared_memory as shm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        # clean slate (mirrors the reference's initial unregister)
        client.unregister_system_shared_memory()

        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.ones((1, 16), dtype=np.int32)
        input_byte_size = input0_data.nbytes
        output_byte_size = input_byte_size

        shm_op = shm.create_shared_memory_region(
            "output_data", "/output_simple", output_byte_size * 2
        )
        client.register_system_shared_memory(
            "output_data", "/output_simple", output_byte_size * 2
        )
        shm_ip = shm.create_shared_memory_region(
            "input_data", "/input_simple", input_byte_size * 2
        )
        shm.set_shared_memory_region(shm_ip, [input0_data, input1_data])
        client.register_system_shared_memory(
            "input_data", "/input_simple", input_byte_size * 2
        )

        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", input_byte_size)
        inputs[1].set_shared_memory("input_data", input_byte_size, offset=input_byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", output_byte_size)
        outputs[1].set_shared_memory("output_data", output_byte_size, offset=output_byte_size)

        client.infer("simple", inputs, outputs=outputs)

        output0 = shm.get_contents_as_numpy(shm_op, np.int32, [1, 16])
        output1 = shm.get_contents_as_numpy(
            shm_op, np.int32, [1, 16], offset=output_byte_size
        )
        for i in range(16):
            if output0[0][i] != input0_data[0][i] + input1_data[0][i]:
                sys.exit("shm infer error: incorrect sum")
            if output1[0][i] != input0_data[0][i] - input1_data[0][i]:
                sys.exit("shm infer error: incorrect difference")

        print(client.get_system_shared_memory_status())
        client.unregister_system_shared_memory()
        shm.destroy_shared_memory_region(shm_ip)
        shm.destroy_shared_memory_region(shm_op)
        print("PASS: system shared memory")


if __name__ == "__main__":
    main()
