// Go generated-stub example for inference.GRPCInferenceService.
//
// Mirrors the reference's src/grpc_generated/go/grpc_simple_client.go
// feature set (dial, ServerLive, ServerReady, ModelMetadata, ModelInfer on
// the "simple" model with raw_input_contents — :66-160 there), written
// fresh against this repo's vendored proto/grpc_service.proto. Generate the
// stub package first with ./gen_go_stubs.sh, then:
//
//	go run grpc_simple_client.go -u localhost:8001
//
// The "simple" model takes two INT32[1,16] tensors and returns their
// elementwise sum (OUTPUT0) and difference (OUTPUT1).
package main

import (
	"context"
	"encoding/binary"
	"flag"
	"fmt"
	"log"
	"time"

	pb "client_tpu_grpc/inference"

	"google.golang.org/grpc"
	"google.golang.org/grpc/credentials/insecure"
)

const (
	modelName = "simple"
	batch     = 1
	elems     = 16
)

// int32sToLE serializes a tensor the way every v2 client does: little-endian
// element bytes, row-major, no header (the shape/datatype ride in the
// InferInputTensor message).
func int32sToLE(vals []int32) []byte {
	raw := make([]byte, 4*len(vals))
	for i, v := range vals {
		binary.LittleEndian.PutUint32(raw[i*4:], uint32(v))
	}
	return raw
}

func leToInt32s(raw []byte) []int32 {
	out := make([]int32, len(raw)/4)
	for i := range out {
		out[i] = int32(binary.LittleEndian.Uint32(raw[i*4:]))
	}
	return out
}

func main() {
	url := flag.String("u", "localhost:8001", "server url host:port")
	timeout := flag.Duration("t", 10*time.Second, "per-rpc deadline")
	flag.Parse()

	conn, err := grpc.NewClient(
		*url, grpc.WithTransportCredentials(insecure.NewCredentials()))
	if err != nil {
		log.Fatalf("dial %s: %v", *url, err)
	}
	defer conn.Close()
	client := pb.NewGRPCInferenceServiceClient(conn)

	ctx, cancel := context.WithTimeout(context.Background(), *timeout)
	defer cancel()

	live, err := client.ServerLive(ctx, &pb.Empty{})
	if err != nil {
		log.Fatalf("ServerLive: %v", err)
	}
	fmt.Printf("server live: %v\n", live.Live)

	ready, err := client.ServerReady(ctx, &pb.Empty{})
	if err != nil {
		log.Fatalf("ServerReady: %v", err)
	}
	fmt.Printf("server ready: %v\n", ready.Ready)

	meta, err := client.ModelMetadata(
		ctx, &pb.ModelMetadataRequest{Name: modelName})
	if err != nil {
		log.Fatalf("ModelMetadata: %v", err)
	}
	fmt.Printf("model %s: inputs=%d outputs=%d\n",
		meta.Name, len(meta.Inputs), len(meta.Outputs))

	input0 := make([]int32, elems)
	input1 := make([]int32, elems)
	for i := range input0 {
		input0[i] = int32(i)
		input1[i] = 1
	}

	req := &pb.ModelInferRequest{
		ModelName: modelName,
		Inputs: []*pb.ModelInferRequest_InferInputTensor{
			{Name: "INPUT0", Datatype: "INT32", Shape: []int64{batch, elems}},
			{Name: "INPUT1", Datatype: "INT32", Shape: []int64{batch, elems}},
		},
		Outputs: []*pb.ModelInferRequest_InferRequestedOutputTensor{
			{Name: "OUTPUT0"},
			{Name: "OUTPUT1"},
		},
		// raw contents pair up with inputs by position
		RawInputContents: [][]byte{int32sToLE(input0), int32sToLE(input1)},
	}

	resp, err := client.ModelInfer(ctx, req)
	if err != nil {
		log.Fatalf("ModelInfer: %v", err)
	}
	if len(resp.RawOutputContents) != 2 {
		log.Fatalf("expected 2 raw outputs, got %d", len(resp.RawOutputContents))
	}
	sum := leToInt32s(resp.RawOutputContents[0])
	diff := leToInt32s(resp.RawOutputContents[1])
	for i := range input0 {
		if sum[i] != input0[i]+input1[i] || diff[i] != input0[i]-input1[i] {
			log.Fatalf("mismatch at %d: %d+%d -> sum=%d diff=%d",
				i, input0[i], input1[i], sum[i], diff[i])
		}
	}
	fmt.Println("PASS: sum/diff verified for all 16 elements")
}
