#!/bin/sh
# Generate the Go stub package for inference.GRPCInferenceService from the
# vendored proto (reference parity: src/grpc_generated/go/gen_go_stubs.sh).
#
# Requires: protoc, protoc-gen-go, protoc-gen-go-grpc on PATH
#   go install google.golang.org/protobuf/cmd/protoc-gen-go@latest
#   go install google.golang.org/grpc/cmd/protoc-gen-go-grpc@latest
set -e
HERE=$(dirname "$0")
PROTO_DIR="$HERE/../../proto"
OUT="$HERE/inference"
mkdir -p "$OUT"
protoc \
  -I "$PROTO_DIR" \
  --go_out="$OUT" --go_opt=paths=source_relative \
  --go_opt=Mgrpc_service.proto=client_tpu_grpc/inference \
  --go-grpc_out="$OUT" --go-grpc_opt=paths=source_relative \
  --go-grpc_opt=Mgrpc_service.proto=client_tpu_grpc/inference \
  "$PROTO_DIR/grpc_service.proto"
echo "stubs written to $OUT"
