module client_tpu_grpc

go 1.21

require (
	google.golang.org/grpc v1.64.0
	google.golang.org/protobuf v1.34.0
)
