#!/usr/bin/env python
"""LLM-style streaming generation: one request, one response per token.

Drives the decoupled ``tiny_lm_generate`` fixture the way an LLM serving
client drives a Triton TensorRT-LLM/vLLM backend: the request carries the
prompt and MAX_TOKENS, the server streams a NEXT_TOKEN response per
generated token, and the client prints tokens as they arrive with a
time-to-first-token measurement. (Reference pattern: decoupled
model_transaction_policy + bi-di ModelStreamInfer; see
simple_grpc_custom_repeat for the generic decoupled fixture.)
"""

import argparse
import queue
import sys
import time

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-p", "--prompt", default="10,20,30,40",
                        help="comma-separated prompt token ids (0-255)")
    parser.add_argument("-n", "--max-tokens", type=int, default=16)
    parser.add_argument("--chunk", type=int, default=1,
                        help="tokens per device dispatch (lax.scan burst)")
    args = parser.parse_args()

    prompt = np.array(
        [[int(t) for t in args.prompt.split(",")]], dtype=np.int32)
    results = queue.Queue()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.start_stream(callback=lambda r, e: results.put((r, e)))
        inputs = [
            grpcclient.InferInput("TOKENS", list(prompt.shape), "INT32"),
            grpcclient.InferInput("MAX_TOKENS", [1], "INT32"),
        ]
        inputs[0].set_data_from_numpy(prompt)
        inputs[1].set_data_from_numpy(
            np.array([args.max_tokens], dtype=np.int32))

        t0 = time.perf_counter()
        client.async_stream_infer(
            "tiny_lm_generate", inputs,
            enable_empty_final_response=True,
            parameters={"chunk": args.chunk} if args.chunk != 1 else None,
        )

        tokens = []
        ttft_ms = None
        while True:
            result, error = results.get(timeout=60)
            if error is not None:
                print(f"stream error: {error}", file=sys.stderr)
                return 1
            if result.is_final_response() and result.is_null_response():
                break
            if ttft_ms is None:
                ttft_ms = (time.perf_counter() - t0) * 1e3
            tok = int(result.as_numpy("NEXT_TOKEN").reshape(-1)[0])
            tokens.append(tok)
            print(f"token[{len(tokens) - 1:>2}] = {tok}", flush=True)
        total_ms = (time.perf_counter() - t0) * 1e3
        client.stop_stream()

    if len(tokens) != args.max_tokens:
        print(f"expected {args.max_tokens} tokens, got {len(tokens)}",
              file=sys.stderr)
        return 1
    rate = len(tokens) / (total_ms / 1e3)
    print(f"TTFT {ttft_ms:.1f} ms, {len(tokens)} tokens in {total_ms:.1f} ms "
          f"({rate:.0f} tok/s)")
    print("PASS: llm_generate_stream")
    return 0


if __name__ == "__main__":
    sys.exit(main())
