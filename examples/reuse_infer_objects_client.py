#!/usr/bin/env python
"""Reusing InferInput/InferRequestedOutput objects across requests and
protocols (equivalent of reuse_infer_objects_client.py)."""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--http-url", default="localhost:8000")
    parser.add_argument("-g", "--grpc-url", default="localhost:8001")
    args = parser.parse_args()

    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    # the value model is shared between protocols: build once, reuse everywhere
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32").set_data_from_numpy(a),
        httpclient.InferInput("INPUT1", [1, 16], "INT32").set_data_from_numpy(b),
    ]
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0"),
        httpclient.InferRequestedOutput("OUTPUT1"),
    ]

    with httpclient.InferenceServerClient(args.http_url) as http_client:
        for _ in range(3):
            result = http_client.infer("simple", inputs, outputs=outputs)
            if not (result.as_numpy("OUTPUT0") == a + b).all():
                sys.exit("reuse error over http")

    with grpcclient.InferenceServerClient(args.grpc_url) as grpc_client:
        for _ in range(3):
            result = grpc_client.infer("simple", inputs, outputs=outputs)
            if not (result.as_numpy("OUTPUT1") == a - b).all():
                sys.exit("reuse error over grpc")

    # mutate in place and reuse again
    inputs[0].set_data_from_numpy(a * 2)
    with httpclient.InferenceServerClient(args.http_url) as http_client:
        result = http_client.infer("simple", inputs, outputs=outputs)
        if not (result.as_numpy("OUTPUT0") == a * 2 + b).all():
            sys.exit("reuse error after mutation")
    print("PASS: object reuse across 7 requests and 2 protocols")


if __name__ == "__main__":
    main()
