#!/usr/bin/env python
"""Image classification client driven by model metadata.

Equivalent of the reference's image_client.py (parse_model :60, preprocess
:154 with NONE/INCEPTION/VGG scaling :174-176, postprocess :196,
HTTP/GRPC/async switches :262-510) — with the preprocessing running through
XLA (client_tpu.ops Pallas normalize kernel) instead of numpy/PIL math.

Works against the bundled densenet_onnx flax model
(``python -m client_tpu.serve --vision``) or a real tritonserver hosting the
densenet_onnx fixture. Input images: .npy arrays (HWC uint8) or, when Pillow
is available, any image file.
"""

import argparse
import sys

import numpy as np


def parse_model(metadata, config):
    """Pull the single input/output contract out of model metadata."""
    if len(metadata["inputs"]) != 1 or len(metadata["outputs"]) != 1:
        sys.exit("expecting a single-input single-output vision model")
    inp = metadata["inputs"][0]
    out = metadata["outputs"][0]
    shape = [d for d in inp["shape"] if d != -1]
    if len(shape) == 3 and shape[0] in (1, 3):
        fmt, c, h, w = "CHW", shape[0], shape[1], shape[2]
    elif len(shape) == 3:
        fmt, h, w, c = "HWC", shape[0], shape[1], shape[2]
    else:
        sys.exit(f"unexpected input shape {inp['shape']}")
    return inp["name"], out["name"], fmt, c, h, w, inp["datatype"]


def load_image(path, h, w):
    if path.endswith(".npy"):
        img = np.load(path)
    else:
        try:
            from PIL import Image
        except ImportError:
            sys.exit("non-.npy images need Pillow; pass a .npy HWC uint8 array")
        img = np.asarray(Image.open(path).convert("RGB").resize((w, h)))
    if img.shape[:2] != (h, w):
        # nearest-neighbor resize without PIL
        ys = (np.linspace(0, img.shape[0] - 1, h)).astype(int)
        xs = (np.linspace(0, img.shape[1] - 1, w)).astype(int)
        img = img[ys][:, xs]
    return img.astype(np.float32)


def preprocess(img, fmt, dtype, scaling):
    """Scaling modes from the reference, fused on-device via the Pallas op."""
    from client_tpu.ops import normalize_image

    if scaling == "INCEPTION":
        arr = np.asarray(normalize_image(img, scale=2.0 / 255.0, shift=-1.0, out_dtype=np.float32))
    elif scaling == "VGG":
        arr = img[..., ::-1] - np.array([123.68, 116.779, 103.939], dtype=np.float32)
    else:
        arr = np.asarray(normalize_image(img, scale=1.0, shift=0.0, out_dtype=np.float32))
    if fmt == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return np.ascontiguousarray(arr, dtype=np.float32)


def postprocess(result, output_name, topk):
    entries = result.as_numpy(output_name)
    if entries is None:
        sys.exit("no classification output returned")
    for entry in entries.reshape(-1)[:topk]:
        value, idx, *label = entry.decode().split(":")
        name = label[0] if label else idx
        print(f"    {float(value):.6f} ({idx}) = {name}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("image", nargs="?", default=None, help=".npy or image file")
    parser.add_argument("-m", "--model-name", default="densenet_onnx")
    parser.add_argument("-u", "--url", default=None)
    parser.add_argument("-i", "--protocol", choices=("http", "grpc"), default="http")
    parser.add_argument("-c", "--classes", type=int, default=3)
    parser.add_argument(
        "-s", "--scaling", choices=("NONE", "INCEPTION", "VGG"), default="INCEPTION"
    )
    parser.add_argument("-a", "--async_run", action="store_true")
    args = parser.parse_args()

    if args.protocol == "http":
        import client_tpu.http as clientmod

        url = args.url or "localhost:8000"
    else:
        import client_tpu.grpc as clientmod

        url = args.url or "localhost:8001"

    kwargs = {"network_timeout": 300.0} if args.protocol.lower() == "http" else {}
    with clientmod.InferenceServerClient(url, **kwargs) as client:
        metadata = client.get_model_metadata(args.model_name)
        config = client.get_model_config(args.model_name)
        input_name, output_name, fmt, c, h, w, dtype = parse_model(metadata, config)

        if args.image:
            img = load_image(args.image, h, w)
        else:
            print("no image supplied; classifying random noise")
            img = np.random.default_rng(0).uniform(0, 255, (h, w, c)).astype(np.float32)

        data = preprocess(img, fmt, dtype, args.scaling)
        inp = clientmod.InferInput(input_name, list(data.shape), dtype)
        inp.set_data_from_numpy(data)
        outputs = [clientmod.InferRequestedOutput(output_name, class_count=args.classes)]

        if args.async_run:
            handle = client.async_infer(args.model_name, [inp], outputs=outputs)
            result = handle.get_result()  # HTTP InferAsyncRequest / GRPC CallContext
        else:
            result = client.infer(args.model_name, [inp], outputs=outputs)
        print(f"Top {args.classes} classes:")
        postprocess(result, output_name, args.classes)
        print("PASS: image_client")


if __name__ == "__main__":
    main()
