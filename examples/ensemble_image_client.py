#!/usr/bin/env python
"""Ensemble pipeline client: raw image in, classification out.

Equivalent of the reference's ensemble_image_client.py — the server-side
ensemble (`ensemble_image`: preprocess -> densenet_onnx) takes the raw UINT8
HWC image; no client-side preprocessing at all.
Requires: ``python -m client_tpu.serve --vision``.
"""

import argparse
import sys

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("image", nargs="?", default=None, help=".npy HWC uint8 image")
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-i", "--protocol", choices=("http", "grpc"), default="http")
    parser.add_argument("-c", "--classes", type=int, default=3)
    args = parser.parse_args()

    if args.protocol == "http":
        import client_tpu.http as clientmod
    else:
        import client_tpu.grpc as clientmod

    if args.image:
        img = np.load(args.image).astype(np.uint8)
    else:
        print("no image supplied; classifying random noise")
        img = np.random.default_rng(0).integers(0, 256, (300, 400, 3)).astype(np.uint8)

    # first request pays the XLA compile of both ensemble stages: give the
    # http read timeout room (a stock tritonserver compiles at load, not
    # request); the grpc client has no read deadline by default
    kwargs = {"network_timeout": 300.0} if args.protocol == "http" else {}
    with clientmod.InferenceServerClient(args.url, **kwargs) as client:
        if not client.is_model_ready("ensemble_image"):
            sys.exit("model 'ensemble_image' not ready (serve with --vision)")
        inp = clientmod.InferInput("IMAGE", list(img.shape), "UINT8")
        inp.set_data_from_numpy(img)
        outputs = [
            clientmod.InferRequestedOutput("CLASSIFICATION", class_count=args.classes)
        ]
        result = client.infer("ensemble_image", [inp], outputs=outputs)
        entries = result.as_numpy("CLASSIFICATION")
        if entries is None or entries.size != args.classes:
            sys.exit("ensemble error: no classification output")
        print(f"Top {args.classes} classes (server-side preprocess + classify):")
        for entry in entries.reshape(-1):
            value, idx, *label = entry.decode().split(":")
            print(f"    {float(value):.6f} ({idx}) = {label[0] if label else idx}")
        print("PASS: ensemble_image_client")


if __name__ == "__main__":
    main()
