"""INT8-quantized wire transport: 4x less bandwidth for FP32 tensors.

The client quantizes an FP32 tensor on-device (Pallas ``quantize_int8``),
ships INT8 bytes over the wire, and dequantizes the response — the classic
bandwidth play for WAN/DCN hops, impossible to express in the reference
client without custom model logic (here it is two client-side ops).

Usage: quantized_wire_client.py [-u HOST:PORT]
"""

import argparse
import sys

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="127.0.0.1:8000")
    args = parser.parse_args()

    import client_tpu.http as httpclient
    from client_tpu.ops import dequantize_int8, quantize_int8

    rng = np.random.default_rng(11)
    x = rng.standard_normal((1, 8192)).astype(np.float32)
    scale = float(np.abs(x).max() / 127.0)

    with httpclient.InferenceServerClient(args.url) as client:
        q = np.asarray(quantize_int8(x, scale))  # 4 bytes -> 1 byte per elem
        inp = httpclient.InferInput("INPUT0", list(q.shape), "INT8")
        inp.set_data_from_numpy(q)
        result = client.infer("identity_int8", [inp])
        q_back = result.as_numpy("OUTPUT0")
        restored = np.asarray(dequantize_int8(q_back, scale))

    err = np.abs(restored - x).max()
    wire_bytes = q.nbytes
    full_bytes = x.nbytes
    print(f"wire payload {wire_bytes} B vs {full_bytes} B fp32 ({full_bytes / wire_bytes:.0f}x smaller)")
    print(f"max dequantization error {err:.6f} (half-step bound {scale / 2:.6f})")
    if err > scale / 2 + 1e-6:
        print("FAIL: dequantization error beyond the quantization step")
        return 1
    print("PASS: quantized_wire_client")
    return 0


if __name__ == "__main__":
    sys.exit(main())
