#!/usr/bin/env python
"""BYTES/string tensors over GRPC (equivalent of simple_grpc_string_infer_client.py)."""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        in0 = np.array([[str(i) for i in range(16)]], dtype=np.object_)
        in1 = np.array([["2"] * 16], dtype=np.object_)
        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "BYTES").set_data_from_numpy(in0),
            grpcclient.InferInput("INPUT1", [1, 16], "BYTES").set_data_from_numpy(in1),
        ]
        result = client.infer("simple_string", inputs)
        out0 = result.as_numpy("OUTPUT0")
        out1 = result.as_numpy("OUTPUT1")
        for i in range(16):
            if int(out0[0][i]) != i + 2 or int(out1[0][i]) != i - 2:
                sys.exit("grpc string infer error")
        print("PASS: grpc string infer")


if __name__ == "__main__":
    main()
