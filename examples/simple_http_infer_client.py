#!/usr/bin/env python
"""Basic sync HTTP inference against the ``simple`` sum/diff model.

Equivalent of the reference's src/python/examples/simple_http_infer_client.py.
Start a server first: ``python -m client_tpu.serve``.
"""

import argparse
import sys

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url, verbose=args.verbose) as client:
        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.ones((1, 16), dtype=np.int32)

        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0_data)
        inputs[1].set_data_from_numpy(input1_data, binary_data=False)

        outputs = [
            httpclient.InferRequestedOutput("OUTPUT0"),
            httpclient.InferRequestedOutput("OUTPUT1", binary_data=False),
        ]
        result = client.infer("simple", inputs, outputs=outputs)

        output0 = result.as_numpy("OUTPUT0")
        output1 = result.as_numpy("OUTPUT1")
        for i in range(16):
            print(f"{input0_data[0][i]} + {input1_data[0][i]} = {output0[0][i]}")
            print(f"{input0_data[0][i]} - {input1_data[0][i]} = {output1[0][i]}")
            if output0[0][i] != input0_data[0][i] + input1_data[0][i]:
                sys.exit("sync infer error: incorrect sum")
            if output1[0][i] != input0_data[0][i] - input1_data[0][i]:
                sys.exit("sync infer error: incorrect difference")
        print("PASS: infer")


if __name__ == "__main__":
    main()
