#!/usr/bin/env python
"""Custom GRPC keepalive options (equivalent of simple_grpc_keepalive_client.py)."""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    keepalive = grpcclient.KeepAliveOptions(
        keepalive_time_ms=10000,
        keepalive_timeout_ms=5000,
        keepalive_permit_without_calls=True,
        http2_max_pings_without_data=0,
    )
    with grpcclient.InferenceServerClient(args.url, keepalive_options=keepalive) as client:
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32").set_data_from_numpy(a),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32").set_data_from_numpy(b),
        ]
        result = client.infer("simple", inputs)
        if not (result.as_numpy("OUTPUT0") == a + b).all():
            sys.exit("keepalive infer error")
        print("PASS: keepalive client")


if __name__ == "__main__":
    main()
