#!/usr/bin/env python
"""System shared-memory inference over HTTP (equivalent of
simple_http_shm_client.py)."""

import argparse
import sys

import numpy as np

import client_tpu.http as httpclient
import client_tpu.utils.shared_memory as shm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        client.unregister_system_shared_memory()
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        nbytes = a.nbytes

        shm_ip = shm.create_shared_memory_region("input_data", "/http_shm_in", 2 * nbytes)
        shm_op = shm.create_shared_memory_region("output_data", "/http_shm_out", 2 * nbytes)
        shm.set_shared_memory_region(shm_ip, [a, b])
        client.register_system_shared_memory("input_data", "/http_shm_in", 2 * nbytes)
        client.register_system_shared_memory("output_data", "/http_shm_out", 2 * nbytes)

        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", nbytes)
        inputs[1].set_shared_memory("input_data", nbytes, offset=nbytes)
        outputs = [
            httpclient.InferRequestedOutput("OUTPUT0"),
            httpclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", nbytes)
        outputs[1].set_shared_memory("output_data", nbytes, offset=nbytes)

        client.infer("simple", inputs, outputs=outputs)
        out0 = shm.get_contents_as_numpy(shm_op, np.int32, [1, 16])
        out1 = shm.get_contents_as_numpy(shm_op, np.int32, [1, 16], offset=nbytes)
        ok = (out0 == a + b).all() and (out1 == a - b).all()

        client.unregister_system_shared_memory()
        shm.destroy_shared_memory_region(shm_ip)
        shm.destroy_shared_memory_region(shm_op)
        if not ok:
            sys.exit("http shm error: incorrect results")
        print("PASS: http system shared memory")


if __name__ == "__main__":
    main()
