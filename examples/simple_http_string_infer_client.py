#!/usr/bin/env python
"""BYTES/string tensors over HTTP (equivalent of simple_http_string_infer_client.py)."""

import argparse
import sys

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        in0 = np.array([[str(i) for i in range(16)]], dtype=np.object_)
        in1 = np.array([["1"] * 16], dtype=np.object_)
        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "BYTES"),
            httpclient.InferInput("INPUT1", [1, 16], "BYTES"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1, binary_data=False)
        result = client.infer("simple_string", inputs)
        output0 = result.as_numpy("OUTPUT0")
        output1 = result.as_numpy("OUTPUT1")
        for i in range(16):
            if int(output0[0][i]) != i + 1 or int(output1[0][i]) != i - 1:
                sys.exit("string infer error: incorrect result")
        print("PASS: string infer")


if __name__ == "__main__":
    main()
