#!/usr/bin/env python
"""Leak harness: repeat inferences and report RSS growth.

Equivalent of the reference's memory_growth_test.py (:28-60): drive N
repetitions, sample resident set size before/after, fail on runaway growth.
"""

import argparse
import gc
import resource
import sys

import numpy as np

import client_tpu.http as httpclient


def _rss_kb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-r", "--repetitions", type=int, default=500)
    parser.add_argument("--max-growth-mb", type=float, default=64.0)
    args = parser.parse_args()

    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)

    with httpclient.InferenceServerClient(args.url, concurrency=2) as client:
        # warm up allocators before baselining
        for _ in range(50):
            inputs = [
                httpclient.InferInput("INPUT0", [1, 16], "INT32").set_data_from_numpy(a),
                httpclient.InferInput("INPUT1", [1, 16], "INT32").set_data_from_numpy(b),
            ]
            client.infer("simple", inputs)
        gc.collect()
        before_kb = _rss_kb()
        for i in range(args.repetitions):
            inputs = [
                httpclient.InferInput("INPUT0", [1, 16], "INT32").set_data_from_numpy(a),
                httpclient.InferInput("INPUT1", [1, 16], "INT32").set_data_from_numpy(b),
            ]
            result = client.infer("simple", inputs)
            assert result.as_numpy("OUTPUT0") is not None
        gc.collect()
        after_kb = _rss_kb()

    growth_mb = (after_kb - before_kb) / 1024.0
    print(f"RSS growth over {args.repetitions} inferences: {growth_mb:.1f} MB")
    if growth_mb > args.max_growth_mb:
        sys.exit(f"FAILED: RSS grew {growth_mb:.1f} MB (limit {args.max_growth_mb} MB)")
    print("PASS: memory growth within bounds")


if __name__ == "__main__":
    main()
