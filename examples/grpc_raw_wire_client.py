#!/usr/bin/env python
"""Raw protocol usage without the client class — the analog of the
reference's generated-stub examples (grpc_image_client.py, grpc_client.py,
src/grpc_generated/*): build ModelInferRequest dicts directly against the
wire codec and call the service through a bare grpc channel."""

import argparse
import sys

import grpc
import numpy as np

from client_tpu.grpc import _messages as M
from client_tpu.grpc._wire import decode_message, encode_message


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)

    def unary(method):
        req_spec, resp_spec = M.METHODS[method]
        return channel.unary_unary(
            M.method_path(method),
            request_serializer=lambda d: encode_message(req_spec, d),
            response_deserializer=lambda b: decode_message(resp_spec, b),
        )

    live = unary("ServerLive")({})
    print("live:", live.get("live"))
    metadata = unary("ModelMetadata")({"name": "simple"})
    print("model:", metadata["name"], [t["name"] for t in metadata["inputs"]])

    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)
    request = {
        "model_name": "simple",
        "inputs": [
            {"name": "INPUT0", "datatype": "INT32", "shape": [1, 16]},
            {"name": "INPUT1", "datatype": "INT32", "shape": [1, 16]},
        ],
        "raw_input_contents": [a.tobytes(), b.tobytes()],
    }
    response = unary("ModelInfer")(request)
    sums = np.frombuffer(response["raw_output_contents"][0], dtype=np.int32)
    if not (sums == (a + b).reshape(-1)).all():
        sys.exit("raw wire infer error")
    channel.close()
    print("PASS: raw wire-codec client")


if __name__ == "__main__":
    main()
