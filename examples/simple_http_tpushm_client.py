#!/usr/bin/env python
"""TPU shared-memory inference over HTTP (the cudashm example, TPU-native)."""

import argparse
import sys

import numpy as np

import client_tpu.http as httpclient
import client_tpu.utils.tpu_shared_memory as tpushm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    import jax.numpy as jnp

    with httpclient.InferenceServerClient(args.url) as client:
        client.unregister_tpu_shared_memory()
        a = jnp.arange(16, dtype=jnp.int32).reshape(1, 16)
        b = jnp.ones((1, 16), jnp.int32)
        nbytes = 64

        rin = tpushm.create_shared_memory_region("input_data", 2 * nbytes)
        rout = tpushm.create_shared_memory_region("output_data", 2 * nbytes)
        tpushm.set_shared_memory_region_from_jax(rin, a)
        tpushm.set_shared_memory_region_from_jax(rin, b, offset=nbytes)
        client.register_tpu_shared_memory("input_data", tpushm.get_raw_handle(rin), 0, 2 * nbytes)
        client.register_tpu_shared_memory("output_data", tpushm.get_raw_handle(rout), 0, 2 * nbytes)

        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", nbytes)
        inputs[1].set_shared_memory("input_data", nbytes, offset=nbytes)
        outputs = [
            httpclient.InferRequestedOutput("OUTPUT0"),
            httpclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", nbytes)
        outputs[1].set_shared_memory("output_data", nbytes, offset=nbytes)

        client.infer("simple", inputs, outputs=outputs)
        sums = np.asarray(tpushm.get_contents_as_jax(rout, "INT32", [1, 16]))
        diffs = tpushm.get_contents_as_numpy(rout, "INT32", [1, 16], offset=nbytes)
        ok = (sums == np.asarray(a + b)).all() and (diffs == np.asarray(a - b)).all()

        client.unregister_tpu_shared_memory()
        tpushm.destroy_shared_memory_region(rin)
        tpushm.destroy_shared_memory_region(rout)
        if not ok:
            sys.exit("http tpu shm error: incorrect results")
        print("PASS: http tpu shared memory")


if __name__ == "__main__":
    main()
