#!/usr/bin/env python
"""TPU shared-memory inference over GRPC — the cudashm example, TPU-native.

Equivalent of the reference's simple_grpc_cudashm_client.py with the CUDA IPC
region replaced by a tpu_shared_memory region: inputs are bound as live
jax.Arrays, outputs are read back through the device path.
"""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.utils.tpu_shared_memory as tpushm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    import jax.numpy as jnp

    with grpcclient.InferenceServerClient(args.url) as client:
        client.unregister_tpu_shared_memory()

        input0_data = jnp.arange(16, dtype=jnp.int32).reshape(1, 16)
        input1_data = jnp.ones((1, 16), jnp.int32)
        nbytes = 64

        shm_ip = tpushm.create_shared_memory_region("input_data", nbytes * 2)
        tpushm.set_shared_memory_region_from_jax(shm_ip, input0_data)
        tpushm.set_shared_memory_region_from_jax(shm_ip, input1_data, offset=nbytes)
        client.register_tpu_shared_memory(
            "input_data", tpushm.get_raw_handle(shm_ip), 0, nbytes * 2
        )
        shm_op = tpushm.create_shared_memory_region("output_data", nbytes * 2)
        client.register_tpu_shared_memory(
            "output_data", tpushm.get_raw_handle(shm_op), 0, nbytes * 2
        )

        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", nbytes)
        inputs[1].set_shared_memory("input_data", nbytes, offset=nbytes)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", nbytes)
        outputs[1].set_shared_memory("output_data", nbytes, offset=nbytes)

        client.infer("simple", inputs, outputs=outputs)

        # device-path read: jax.Array without a wire hop
        output0 = np.asarray(tpushm.get_contents_as_jax(shm_op, "INT32", [1, 16]))
        output1 = tpushm.get_contents_as_numpy(shm_op, "INT32", [1, 16], offset=nbytes)
        expected0 = np.asarray(input0_data + input1_data)
        expected1 = np.asarray(input0_data - input1_data)
        if not ((output0 == expected0).all() and (output1 == expected1).all()):
            sys.exit("tpu shm infer error: incorrect results")

        print(client.get_tpu_shared_memory_status())
        client.unregister_tpu_shared_memory()
        tpushm.destroy_shared_memory_region(shm_ip)
        tpushm.destroy_shared_memory_region(shm_op)
        print("PASS: tpu shared memory")


if __name__ == "__main__":
    main()
