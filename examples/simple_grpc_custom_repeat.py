#!/usr/bin/env python
"""Decoupled-model streaming: N responses per request.

Equivalent of the reference's simple_grpc_custom_repeat.py against the
``repeat_int32`` fixture.
"""

import argparse
import queue
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-r", "--repeat-count", type=int, default=5)
    args = parser.parse_args()

    values = np.arange(args.repeat_count, dtype=np.int32)
    results = queue.Queue()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.start_stream(callback=lambda r, e: results.put((r, e)))
        inputs = [
            grpcclient.InferInput("IN", [args.repeat_count], "INT32"),
            grpcclient.InferInput("DELAY", [args.repeat_count], "UINT32"),
            grpcclient.InferInput("WAIT", [1], "UINT32"),
        ]
        inputs[0].set_data_from_numpy(values)
        inputs[1].set_data_from_numpy(np.zeros(args.repeat_count, dtype=np.uint32))
        inputs[2].set_data_from_numpy(np.array([0], dtype=np.uint32))
        client.async_stream_infer(
            "repeat_int32", inputs, enable_empty_final_response=True
        )
        seen = []
        while True:
            result, error = results.get(timeout=30)
            if error is not None:
                sys.exit(f"stream error: {error}")
            if result.is_null_response():
                break
            seen.append(int(result.as_numpy("OUT")[0]))
        client.stop_stream()

    if seen != values.tolist():
        sys.exit(f"repeat error: {seen} != {values.tolist()}")
    print(f"PASS: decoupled repeat ({len(seen)} responses + final)")


if __name__ == "__main__":
    main()
