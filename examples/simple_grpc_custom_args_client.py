#!/usr/bin/env python
"""Fully custom GRPC channel args (equivalent of simple_grpc_custom_args_client.py)."""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    # channel_args fully replaces the defaults (reference behavior)
    channel_args = [
        ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ("grpc.max_receive_message_length", 64 * 1024 * 1024),
        ("grpc.primary_user_agent", "client_tpu_custom_args_example"),
    ]
    with grpcclient.InferenceServerClient(args.url, channel_args=channel_args) as client:
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.ones((1, 16), dtype=np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32").set_data_from_numpy(a),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32").set_data_from_numpy(b),
        ]
        result = client.infer("simple", inputs)
        if not (result.as_numpy("OUTPUT0") == a + b).all():
            sys.exit("custom args infer error")
        print("PASS: custom channel args")


if __name__ == "__main__":
    main()
