#!/usr/bin/env python
"""Basic sync GRPC inference against the ``simple`` sum/diff model.

Equivalent of the reference's simple_grpc_infer_client.py.
"""

import argparse
import sys

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url, verbose=args.verbose) as client:
        input0_data = np.arange(16, dtype=np.int32).reshape(1, 16)
        input1_data = np.ones((1, 16), dtype=np.int32)

        inputs = [
            grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
            grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0_data)
        inputs[1].set_data_from_numpy(input1_data)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        result = client.infer("simple", inputs, outputs=outputs)
        output0 = result.as_numpy("OUTPUT0")
        output1 = result.as_numpy("OUTPUT1")
        if not ((output0 == input0_data + input1_data).all()
                and (output1 == input0_data - input1_data).all()):
            sys.exit("grpc infer error: incorrect results")
        print("PASS: infer")


if __name__ == "__main__":
    main()
