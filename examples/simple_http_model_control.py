#!/usr/bin/env python
"""Model repository control: index/unload/load (equivalent of
simple_http_model_control.py)."""

import argparse
import sys

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        index = client.get_model_repository_index()
        print("repository:", [(m["name"], m["state"]) for m in index])
        client.unload_model("simple_string")
        if client.is_model_ready("simple_string"):
            sys.exit("FAILED: model still ready after unload")
        client.load_model("simple_string")
        if not client.is_model_ready("simple_string"):
            sys.exit("FAILED: model not ready after load")
        print("PASS: model control")


if __name__ == "__main__":
    main()
