#!/usr/bin/env python
"""asyncio bidi sequence streaming (equivalent of
simple_grpc_aio_sequence_stream_infer_client.py)."""

import argparse
import asyncio
import sys

import numpy as np

import client_tpu.grpc.aio as grpcclient


async def run(url):
    values = [10, 20, 30]
    async with grpcclient.InferenceServerClient(url) as client:
        async def requests():
            for i, v in enumerate(values):
                inp = grpcclient.InferInput("INPUT", [1, 1], "INT32")
                inp.set_data_from_numpy(np.array([[v]], dtype=np.int32))
                yield {
                    "model_name": "simple_sequence",
                    "inputs": [inp],
                    "sequence_id": 4001,
                    "sequence_start": i == 0,
                    "sequence_end": i == len(values) - 1,
                }

        stream = await client.stream_infer(requests())
        running = []
        async for result, error in stream:
            if error is not None:
                sys.exit(f"stream error: {error}")
            running.append(int(result.as_numpy("OUTPUT")[0, 0]))
        expected = list(np.cumsum(values))
        if running != expected:
            sys.exit(f"aio sequence error: {running} != {expected}")
    print(f"PASS: aio sequence stream (partials {running})")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()
    asyncio.run(run(args.url))


if __name__ == "__main__":
    main()
