"""The native C++ GRPC client from Python: ctypes over the hand-rolled h2.

Demonstrates the `client_tpu.native.NativeGrpcClient` binding — the same
value-model surface as the C++ `InferenceServerGrpcClient` (gRPC framed by
hand over the library's own HTTP/2+HPACK transport, native/src/h2.cc), with
results decoded back into numpy. Role parity: the reference's C++
simple_grpc_infer_client.cc driven through FFI.

Usage: simple_native_grpc_client.py [-u HOST:PORT]
"""

import argparse
import sys

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="127.0.0.1:8001")
    args = parser.parse_args()

    from client_tpu.native import NativeGrpcClient, available

    if not available():
        # a real failure, not a silent pass: the smoke tier gates on the
        # native build (tests/test_examples.py skips when it's absent)
        print("FAIL: native library not built (cmake -S native -B native/build)")
        return 1

    a = np.arange(16, dtype=np.int32).reshape(1, 16)
    b = np.ones((1, 16), dtype=np.int32)

    with NativeGrpcClient(args.url) as client:
        if not client.is_server_live():
            print("FAIL: server not live")
            return 1
        if not client.is_model_ready("simple"):
            print("FAIL: model 'simple' not ready")
            return 1

        out = client.infer(
            "simple", [("INPUT0", a), ("INPUT1", b)],
            outputs=["OUTPUT0", "OUTPUT1"], request_id="native-grpc-1",
            client_timeout_s=30.0,
        )
        if not (out["OUTPUT0"] == a + b).all():
            print("FAIL: OUTPUT0 mismatch")
            return 1
        if not (out["OUTPUT1"] == a - b).all():
            print("FAIL: OUTPUT1 mismatch")
            return 1
        print("0 + 1 =", out["OUTPUT0"].reshape(-1)[:4], "...")
        print("0 - 1 =", out["OUTPUT1"].reshape(-1)[:4], "...")

        # typed error mapping carries the true grpc status
        try:
            client.infer("missing_model", [("INPUT0", a)])
            print("FAIL: expected an error for the unknown model")
            return 1
        except Exception as e:
            if "StatusCode" not in str(e):
                print(f"FAIL: error lacks a grpc status: {e}")
                return 1
            print("unknown model ->", str(e)[:60])

    print("PASS: simple_native_grpc_client")
    return 0


if __name__ == "__main__":
    sys.exit(main())
