package client_tpu;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * A decoded inference response: the JSON header plus an offset map into the
 * binary tail (reference: src/java/.../InferResult.java). Binary outputs
 * stay as views until a typed getter copies them out little-endian.
 */
public class InferResult {
  private final Json header;
  private final byte[] body;
  private final Map<String, int[]> binarySpans = new LinkedHashMap<>();
  private final Map<String, Json> outputsByName = new LinkedHashMap<>();

  InferResult(byte[] responseBody, int headerLength)
      throws InferenceServerException {
    int jsonLength = headerLength > 0 ? headerLength : responseBody.length;
    if (jsonLength > responseBody.length) {
      throw new InferenceServerException(
          "Inference-Header-Content-Length " + jsonLength
          + " exceeds the body (" + responseBody.length + " bytes)");
    }
    this.body = responseBody;
    this.header = Json.parse(
        new String(responseBody, 0, jsonLength, StandardCharsets.UTF_8));
    int cursor = jsonLength;
    Json outputs = header.get("outputs");
    for (int i = 0; i < outputs.size(); i++) {
      Json output = outputs.get(i);
      String name = output.get("name").asString();
      outputsByName.put(name, output);
      Json size = output.get("parameters").get("binary_data_size");
      if (!size.isNull()) {
        long n = size.asLong();
        if (n < 0 || cursor + n > responseBody.length) {
          throw new InferenceServerException(
              "invalid binary_data_size " + n + " for output '" + name + "'");
        }
        binarySpans.put(name, new int[] {cursor, (int) n});
        cursor += (int) n;
      }
    }
  }

  public String getModelName() { return header.get("model_name").asString(); }
  public String getId() { return header.get("id").asString(); }

  public List<String> getOutputNames() {
    return new ArrayList<>(outputsByName.keySet());
  }

  public long[] getShape(String name) throws InferenceServerException {
    Json output = require(name);
    Json dims = output.get("shape");
    long[] shape = new long[dims.size()];
    for (int i = 0; i < shape.length; i++) shape[i] = dims.get(i).asLong();
    return shape;
  }

  public DataType getDatatype(String name) throws InferenceServerException {
    return DataType.valueOf(require(name).get("datatype").asString());
  }

  private Json require(String name) throws InferenceServerException {
    Json output = outputsByName.get(name);
    if (output == null) {
      throw new InferenceServerException("unknown output '" + name + "'");
    }
    return output;
  }

  private ByteBuffer binary(String name) throws InferenceServerException {
    require(name);
    int[] span = binarySpans.get(name);
    if (span == null) {
      throw new InferenceServerException(
          "output '" + name + "' has no binary data (JSON or shared memory)");
    }
    return ByteBuffer.wrap(body, span[0], span[1])
        .order(ByteOrder.LITTLE_ENDIAN);
  }

  public byte[] getRaw(String name) throws InferenceServerException {
    ByteBuffer buf = binary(name);
    byte[] out = new byte[buf.remaining()];
    buf.get(out);
    return out;
  }

  public int[] getAsInt(String name) throws InferenceServerException {
    ByteBuffer buf = binary(name);
    int[] out = new int[buf.remaining() / 4];
    for (int i = 0; i < out.length; i++) out[i] = buf.getInt();
    return out;
  }

  public long[] getAsLong(String name) throws InferenceServerException {
    ByteBuffer buf = binary(name);
    long[] out = new long[buf.remaining() / 8];
    for (int i = 0; i < out.length; i++) out[i] = buf.getLong();
    return out;
  }

  public float[] getAsFloat(String name) throws InferenceServerException {
    ByteBuffer buf = binary(name);
    float[] out = new float[buf.remaining() / 4];
    for (int i = 0; i < out.length; i++) out[i] = buf.getFloat();
    return out;
  }

  public double[] getAsDouble(String name) throws InferenceServerException {
    ByteBuffer buf = binary(name);
    double[] out = new double[buf.remaining() / 8];
    for (int i = 0; i < out.length; i++) out[i] = buf.getDouble();
    return out;
  }

  /** BYTES outputs (classification labels included): 4-byte LE length
   * prefix per element. Falls back to JSON-mode data when the server
   * answered without binary encoding. */
  public String[] getAsString(String name) throws InferenceServerException {
    Json output = require(name);
    if (binarySpans.containsKey(name)) {
      ByteBuffer buf = binary(name);
      List<String> out = new ArrayList<>();
      while (buf.remaining() >= 4) {
        int n = buf.getInt();
        if (n < 0 || n > buf.remaining()) {
          throw new InferenceServerException(
              "corrupt BYTES element length " + n + " in '" + name + "'");
        }
        byte[] raw = new byte[n];
        buf.get(raw);
        out.add(new String(raw, StandardCharsets.UTF_8));
      }
      return out.toArray(new String[0]);
    }
    Json data = output.get("data");
    String[] out = new String[data.size()];
    for (int i = 0; i < out.length; i++) out[i] = data.get(i).asString();
    return out;
  }

  public Json getResponseHeader() { return header; }
}
