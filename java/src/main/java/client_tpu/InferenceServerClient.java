package client_tpu;

import java.io.IOException;
import java.net.URI;
import java.net.URLEncoder;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.ByteBuffer;
import java.nio.charset.StandardCharsets;
import java.time.Duration;
import java.util.List;
import java.util.Map;
import java.util.concurrent.CompletableFuture;

/**
 * KServe v2 HTTP client on the JDK-11 standard {@code java.net.http} stack.
 *
 * Role parity with the reference Java client
 * (src/java/.../InferenceServerClient.java:76-361, Apache HttpAsyncClient +
 * fastjson + a hand-rolled retry loop) — re-designed dependency-free:
 * java.net.http pools connections and supplies async natively
 * ({@link #inferAsync} returns a {@link CompletableFuture} instead of the
 * reference's callback pool), and the two-part binary body rides the same
 * {@code Inference-Header-Content-Length} contract as every other client in
 * this framework.
 *
 * STATUS: source-complete but untested in this build image (no JDK is
 * installed — see java/README.md). The wire format it emits is the same one
 * the Python/C++ clients emit and the in-process server round-trips in CI.
 */
public class InferenceServerClient implements AutoCloseable {
  private final String baseUrl;
  private final HttpClient http;
  private final Duration requestTimeout;
  private final int retryCnt;

  public InferenceServerClient(String url) {
    this(url, Duration.ofSeconds(5), Duration.ofSeconds(60));
  }

  public InferenceServerClient(
      String url, Duration connectTimeout, Duration requestTimeout) {
    this(url, connectTimeout, requestTimeout, 0);
  }

  /**
   * @param retryCnt additional attempts after a transport failure on
   *     {@link #infer}: the request is retried up to {@code retryCnt} times
   *     and the LAST failure is rethrown (reference semantics,
   *     InferenceServerClient.java:293-317 — transient network errors on an
   *     idempotent infer POST are absorbed, protocol errors are not).
   */
  public InferenceServerClient(
      String url, Duration connectTimeout, Duration requestTimeout,
      int retryCnt) {
    this.baseUrl = url.startsWith("http") ? url : "http://" + url;
    this.requestTimeout = requestTimeout;
    this.retryCnt = Math.max(retryCnt, 0);
    this.http = HttpClient.newBuilder()
        .version(HttpClient.Version.HTTP_1_1)
        .connectTimeout(connectTimeout)
        .build();
  }

  // -- health / metadata ----------------------------------------------------

  public boolean isServerLive() throws InferenceServerException {
    return getStatus("/v2/health/live") == 200;
  }

  public boolean isServerReady() throws InferenceServerException {
    return getStatus("/v2/health/ready") == 200;
  }

  public boolean isModelReady(String modelName) throws InferenceServerException {
    return getStatus("/v2/models/" + seg(modelName) + "/ready") == 200;
  }

  /** Percent-encode one path segment (the Python client quote()s the
   * same way, so names with '/', ' ', '#' stay addressable). */
  private static String seg(String name) {
    return URLEncoder.encode(name, StandardCharsets.UTF_8)
        .replace("+", "%20");
  }

  public Json getServerMetadata() throws InferenceServerException {
    return getJson("/v2");
  }

  public Json getModelMetadata(String modelName) throws InferenceServerException {
    return getJson("/v2/models/" + seg(modelName));
  }

  public Json getModelConfig(String modelName) throws InferenceServerException {
    return getJson("/v2/models/" + seg(modelName) + "/config");
  }

  public Json getModelRepositoryIndex() throws InferenceServerException {
    return postJson("/v2/repository/index", "{}");
  }

  public Json getInferenceStatistics(String modelName)
      throws InferenceServerException {
    return getJson("/v2/models/" + seg(modelName) + "/stats");
  }

  public void loadModel(String modelName) throws InferenceServerException {
    postJson("/v2/repository/models/" + seg(modelName) + "/load", "{}");
  }

  public void unloadModel(String modelName) throws InferenceServerException {
    postJson("/v2/repository/models/" + seg(modelName) + "/unload", "{}");
  }

  // -- shared memory --------------------------------------------------------

  public void registerSystemSharedMemory(
      String name, String key, long byteSize, long offset)
      throws InferenceServerException {
    Json req = Json.object()
        .put("key", Json.of(key))
        .put("offset", Json.of(offset))
        .put("byte_size", Json.of(byteSize));
    postJson(
        "/v2/systemsharedmemory/region/" + seg(name) + "/register", req.dump());
  }

  public void unregisterSystemSharedMemory(String name)
      throws InferenceServerException {
    String path = name.isEmpty()
        ? "/v2/systemsharedmemory/unregister"
        : "/v2/systemsharedmemory/region/" + seg(name) + "/unregister";
    postJson(path, "{}");
  }

  public Json getSystemSharedMemoryStatus() throws InferenceServerException {
    return getJson("/v2/systemsharedmemory/status");
  }

  public void registerTpuSharedMemory(
      String name, String rawHandleBase64, int deviceId, long byteSize)
      throws InferenceServerException {
    Json handle = Json.object().put("b64", Json.of(rawHandleBase64));
    Json req = Json.object()
        .put("raw_handle", handle)
        .put("device_id", Json.of((long) deviceId))
        .put("byte_size", Json.of(byteSize));
    postJson("/v2/tpusharedmemory/region/" + seg(name) + "/register", req.dump());
  }

  public void unregisterTpuSharedMemory(String name)
      throws InferenceServerException {
    String path = name.isEmpty()
        ? "/v2/tpusharedmemory/unregister"
        : "/v2/tpusharedmemory/region/" + seg(name) + "/unregister";
    postJson(path, "{}");
  }

  // -- inference ------------------------------------------------------------

  public InferResult infer(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) throws InferenceServerException {
    return infer(modelName, inputs, outputs, null);
  }

  /** Async twin of {@link #infer}; completes exceptionally with
   * {@link InferenceServerException} on protocol errors. */
  public CompletableFuture<InferResult> inferAsync(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) {
    HttpRequest request;
    try {
      request = buildInferRequest(modelName, inputs, outputs, null);
    } catch (InferenceServerException e) {
      return CompletableFuture.failedFuture(e);
    }
    return http.sendAsync(request, HttpResponse.BodyHandlers.ofByteArray())
        .thenApply(response -> {
          try {
            return decodeInferResponse(response);
          } catch (InferenceServerException e) {
            throw new java.util.concurrent.CompletionException(e);
          }
        });
  }

  public InferResult infer(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs, Map<String, String> headers)
      throws InferenceServerException {
    HttpRequest request = buildInferRequest(modelName, inputs, outputs, headers);
    // Transport failures retry up to retryCnt times (reference
    // InferenceServerClient.java:293-317); server-side errors surface
    // through decodeInferResponse without a retry — they are answers,
    // not transient failures.
    for (int attempt = 0; ; attempt++) {
      try {
        HttpResponse<byte[]> response =
            http.send(request, HttpResponse.BodyHandlers.ofByteArray());
        return decodeInferResponse(response);
      } catch (IOException e) {
        if (attempt >= retryCnt) {
          throw new InferenceServerException(
              "infer request failed after " + (attempt + 1) + " attempt(s): "
                  + e, e);
        }
      } catch (InterruptedException e) {
        Thread.currentThread().interrupt();
        throw new InferenceServerException("infer request interrupted: " + e, e);
      }
    }
  }

  // -- internals ------------------------------------------------------------

  private HttpRequest buildInferRequest(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs, Map<String, String> extraHeaders)
      throws InferenceServerException {
    Json header = Json.object();
    Json inputDescriptors = Json.array();
    long binaryBytes = 0;
    for (InferInput input : inputs) {
      inputDescriptors.append(input.descriptor());
      if (!input.inSharedMemory() && input.getData() != null) {
        binaryBytes += input.getData().length;
      }
    }
    header.put("inputs", inputDescriptors);
    if (outputs != null && !outputs.isEmpty()) {
      Json outputDescriptors = Json.array();
      for (InferRequestedOutput output : outputs) {
        outputDescriptors.append(output.descriptor());
      }
      header.put("outputs", outputDescriptors);
    } else {
      header.put(
          "parameters",
          Json.object().put("binary_data_output", Json.of(true)));
    }

    byte[] headerBytes = header.dump().getBytes(StandardCharsets.UTF_8);
    long totalBytes = headerBytes.length + binaryBytes;
    if (totalBytes > Integer.MAX_VALUE) {
      throw new InferenceServerException(
          "request body of " + totalBytes + " bytes exceeds the 2 GiB limit;"
          + " place large tensors in shared memory instead");
    }
    ByteBuffer body = ByteBuffer.allocate((int) totalBytes);
    body.put(headerBytes);
    for (InferInput input : inputs) {
      if (!input.inSharedMemory() && input.getData() != null) {
        body.put(input.getData());
      }
    }

    HttpRequest.Builder builder = HttpRequest.newBuilder()
        .uri(URI.create(baseUrl + "/v2/models/" + seg(modelName) + "/infer"))
        .timeout(requestTimeout)
        .header("Content-Type", "application/octet-stream")
        .header(
            "Inference-Header-Content-Length",
            Integer.toString(headerBytes.length))
        .POST(HttpRequest.BodyPublishers.ofByteArray(body.array()));
    if (extraHeaders != null) {
      for (Map.Entry<String, String> e : extraHeaders.entrySet()) {
        builder.header(e.getKey(), e.getValue());
      }
    }
    return builder.build();
  }

  private InferResult decodeInferResponse(HttpResponse<byte[]> response)
      throws InferenceServerException {
    if (response.statusCode() >= 400) {
      String message = new String(response.body(), StandardCharsets.UTF_8);
      try {
        Json error = Json.parse(message);
        if (error.has("error")) message = error.get("error").asString();
      } catch (InferenceServerException ignored) {
        // non-JSON error body: report it verbatim
      }
      throw new InferenceServerException(message, response.statusCode());
    }
    int headerLength = 0;
    String lengthHeader = response.headers()
        .firstValue("Inference-Header-Content-Length")
        .orElse(null);
    if (lengthHeader != null) {
      try {
        headerLength = Integer.parseInt(lengthHeader);
      } catch (NumberFormatException e) {
        throw new InferenceServerException(
            "malformed Inference-Header-Content-Length: " + lengthHeader);
      }
    }
    return new InferResult(response.body(), headerLength);
  }

  private int getStatus(String path) throws InferenceServerException {
    try {
      HttpRequest request = HttpRequest.newBuilder()
          .uri(URI.create(baseUrl + path))
          .timeout(requestTimeout)
          .GET()
          .build();
      return http.send(request, HttpResponse.BodyHandlers.discarding())
          .statusCode();
    } catch (IOException | InterruptedException e) {
      throw new InferenceServerException("request failed: " + e, e);
    }
  }

  private Json getJson(String path) throws InferenceServerException {
    return exchange(path, null);
  }

  private Json postJson(String path, String body)
      throws InferenceServerException {
    return exchange(path, body);
  }

  private Json exchange(String path, String postBody)
      throws InferenceServerException {
    try {
      HttpRequest.Builder builder = HttpRequest.newBuilder()
          .uri(URI.create(baseUrl + path))
          .timeout(requestTimeout);
      HttpRequest request = (postBody == null
          ? builder.GET()
          : builder.header("Content-Type", "application/json")
              .POST(HttpRequest.BodyPublishers.ofString(postBody)))
          .build();
      HttpResponse<String> response =
          http.send(request, HttpResponse.BodyHandlers.ofString());
      if (response.statusCode() >= 400) {
        String message = response.body();
        try {
          Json error = Json.parse(message);
          if (error.has("error")) message = error.get("error").asString();
        } catch (InferenceServerException ignored) {
          // keep the raw body
        }
        throw new InferenceServerException(message, response.statusCode());
      }
      String body = response.body();
      return body == null || body.isEmpty() ? Json.object() : Json.parse(body);
    } catch (IOException | InterruptedException e) {
      throw new InferenceServerException("request failed: " + e, e);
    }
  }

  @Override
  public void close() {
    // java.net.http clients hold daemon threads; nothing to release
  }
}
