package client_tpu;

/** Triton/KServe v2 tensor datatypes with wire sizes (reference:
 * src/java/.../pojo/DataType.java; sizes per the binary tensor
 * extension — all little-endian). */
public enum DataType {
  BOOL(1),
  UINT8(1),
  UINT16(2),
  UINT32(4),
  UINT64(8),
  INT8(1),
  INT16(2),
  INT32(4),
  INT64(8),
  FP16(2),
  FP32(4),
  FP64(8),
  BF16(2),
  BYTES(-1);  // 4-byte LE length prefix per element

  private final int byteSize;

  DataType(int byteSize) { this.byteSize = byteSize; }

  /** Bytes per element; -1 for variable-size BYTES. */
  public int byteSize() { return byteSize; }
}
