package client_tpu;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * Minimal JSON value + recursive-descent parser + writer.
 *
 * Dependency-free by design: the reference Java client pulls in fastjson
 * (src/java/pom.xml); this package stays standard-library-only, the same
 * choice the native library makes with its self-contained Json class.
 */
public final class Json {
  public enum Type { NULL, BOOL, NUMBER, STRING, ARRAY, OBJECT }

  private final Type type;
  private boolean boolValue;
  private double numberValue;
  // int64 JSON values (sequence ids, shm byte sizes) above 2^53 lose
  // precision through double; integral numbers keep an exact long twin
  // (the reference's fastjson Java client preserves longs the same way)
  private long longValue;
  private boolean integral;
  private String stringValue;
  private List<Json> arrayValue;
  private Map<String, Json> objectValue;

  private Json(Type type) { this.type = type; }

  public static Json ofNull() { return new Json(Type.NULL); }

  public static Json of(boolean v) {
    Json j = new Json(Type.BOOL);
    j.boolValue = v;
    return j;
  }

  public static Json of(double v) {
    Json j = new Json(Type.NUMBER);
    j.numberValue = v;
    return j;
  }

  public static Json of(long v) {
    Json j = new Json(Type.NUMBER);
    j.numberValue = v;
    j.longValue = v;
    j.integral = true;
    return j;
  }

  public static Json of(String v) {
    Json j = new Json(Type.STRING);
    j.stringValue = v;
    return j;
  }

  public static Json array() {
    Json j = new Json(Type.ARRAY);
    j.arrayValue = new ArrayList<>();
    return j;
  }

  public static Json object() {
    Json j = new Json(Type.OBJECT);
    j.objectValue = new LinkedHashMap<>();
    return j;
  }

  public Type type() { return type; }
  public boolean isNull() { return type == Type.NULL; }
  public boolean asBool() { return type == Type.BOOL && boolValue; }
  public double asDouble() { return type == Type.NUMBER ? numberValue : 0.0; }
  public long asLong() {
    if (type != Type.NUMBER) return 0L;
    return integral ? longValue : (long) numberValue;
  }
  public String asString() { return type == Type.STRING ? stringValue : ""; }

  public int size() { return type == Type.ARRAY ? arrayValue.size() : 0; }
  public Json get(int index) { return arrayValue.get(index); }
  public Json append(Json v) {
    arrayValue.add(v);
    return this;
  }

  public boolean has(String key) {
    return type == Type.OBJECT && objectValue.containsKey(key);
  }

  /** Member lookup; a NULL Json when absent (never Java null). */
  public Json get(String key) {
    if (type == Type.OBJECT) {
      Json v = objectValue.get(key);
      if (v != null) return v;
    }
    return ofNull();
  }

  public Json put(String key, Json v) {
    objectValue.put(key, v);
    return this;
  }

  public Map<String, Json> members() { return objectValue; }

  // -- writer --------------------------------------------------------------

  public String dump() {
    StringBuilder sb = new StringBuilder();
    write(sb);
    return sb.toString();
  }

  private void write(StringBuilder sb) {
    switch (type) {
      case NULL: sb.append("null"); break;
      case BOOL: sb.append(boolValue); break;
      case NUMBER:
        if (integral) {
          sb.append(longValue);
        } else if (numberValue == Math.floor(numberValue)
            && !Double.isInfinite(numberValue)
            && Math.abs(numberValue) < 9.007199254740992E15) {
          sb.append((long) numberValue);
        } else {
          sb.append(numberValue);
        }
        break;
      case STRING: writeString(sb, stringValue); break;
      case ARRAY: {
        sb.append('[');
        for (int i = 0; i < arrayValue.size(); i++) {
          if (i > 0) sb.append(',');
          arrayValue.get(i).write(sb);
        }
        sb.append(']');
        break;
      }
      case OBJECT: {
        sb.append('{');
        boolean first = true;
        for (Map.Entry<String, Json> e : objectValue.entrySet()) {
          if (!first) sb.append(',');
          first = false;
          writeString(sb, e.getKey());
          sb.append(':');
          e.getValue().write(sb);
        }
        sb.append('}');
        break;
      }
    }
  }

  private static void writeString(StringBuilder sb, String s) {
    sb.append('"');
    for (int i = 0; i < s.length(); i++) {
      char c = s.charAt(i);
      switch (c) {
        case '"': sb.append("\\\""); break;
        case '\\': sb.append("\\\\"); break;
        case '\n': sb.append("\\n"); break;
        case '\r': sb.append("\\r"); break;
        case '\t': sb.append("\\t"); break;
        default:
          if (c < 0x20) {
            sb.append(String.format("\\u%04x", (int) c));
          } else {
            sb.append(c);
          }
      }
    }
    sb.append('"');
  }

  // -- parser --------------------------------------------------------------

  public static Json parse(String text) throws InferenceServerException {
    Parser p = new Parser(text);
    Json value = p.parseValue();
    p.skipWhitespace();
    if (!p.atEnd()) {
      throw new InferenceServerException("trailing JSON content at " + p.pos);
    }
    return value;
  }

  private static final class Parser {
    private final String text;
    private int pos = 0;

    Parser(String text) { this.text = text; }

    boolean atEnd() { return pos >= text.length(); }

    void skipWhitespace() {
      while (pos < text.length() && Character.isWhitespace(text.charAt(pos))) {
        pos++;
      }
    }

    char peek() throws InferenceServerException {
      if (atEnd()) throw new InferenceServerException("truncated JSON");
      return text.charAt(pos);
    }

    void expect(char c) throws InferenceServerException {
      if (atEnd() || text.charAt(pos) != c) {
        throw new InferenceServerException(
            "expected '" + c + "' at offset " + pos);
      }
      pos++;
    }

    Json parseValue() throws InferenceServerException {
      skipWhitespace();
      char c = peek();
      switch (c) {
        case '{': return parseObject();
        case '[': return parseArray();
        case '"': return Json.of(parseString());
        case 't': expectWord("true"); return Json.of(true);
        case 'f': expectWord("false"); return Json.of(false);
        case 'n': expectWord("null"); return Json.ofNull();
        default: return parseNumber();
      }
    }

    void expectWord(String word) throws InferenceServerException {
      if (!text.startsWith(word, pos)) {
        throw new InferenceServerException("bad JSON literal at " + pos);
      }
      pos += word.length();
    }

    Json parseObject() throws InferenceServerException {
      expect('{');
      Json obj = Json.object();
      skipWhitespace();
      if (peek() == '}') {
        pos++;
        return obj;
      }
      while (true) {
        skipWhitespace();
        String key = parseString();
        skipWhitespace();
        expect(':');
        obj.put(key, parseValue());
        skipWhitespace();
        char c = peek();
        pos++;
        if (c == '}') return obj;
        if (c != ',') {
          throw new InferenceServerException("expected ',' or '}' at " + pos);
        }
      }
    }

    Json parseArray() throws InferenceServerException {
      expect('[');
      Json arr = Json.array();
      skipWhitespace();
      if (peek() == ']') {
        pos++;
        return arr;
      }
      while (true) {
        arr.append(parseValue());
        skipWhitespace();
        char c = peek();
        pos++;
        if (c == ']') return arr;
        if (c != ',') {
          throw new InferenceServerException("expected ',' or ']' at " + pos);
        }
      }
    }

    String parseString() throws InferenceServerException {
      expect('"');
      StringBuilder sb = new StringBuilder();
      while (true) {
        char c = peek();
        pos++;
        if (c == '"') return sb.toString();
        if (c == '\\') {
          char esc = peek();
          pos++;
          switch (esc) {
            case '"': sb.append('"'); break;
            case '\\': sb.append('\\'); break;
            case '/': sb.append('/'); break;
            case 'b': sb.append('\b'); break;
            case 'f': sb.append('\f'); break;
            case 'n': sb.append('\n'); break;
            case 'r': sb.append('\r'); break;
            case 't': sb.append('\t'); break;
            case 'u': {
              if (pos + 4 > text.length()) {
                throw new InferenceServerException("truncated \\u escape");
              }
              int code = 0;
              for (int k = 0; k < 4; k++) {
                int digit = Character.digit(text.charAt(pos + k), 16);
                if (digit < 0) {
                  throw new InferenceServerException(
                      "bad \\u escape at " + pos);
                }
                code = (code << 4) | digit;
              }
              sb.append((char) code);
              pos += 4;
              break;
            }
            default:
              throw new InferenceServerException("bad escape at " + pos);
          }
        } else {
          sb.append(c);
        }
      }
    }

    Json parseNumber() throws InferenceServerException {
      int start = pos;
      while (pos < text.length()
          && "+-0123456789.eE".indexOf(text.charAt(pos)) >= 0) {
        pos++;
      }
      String token = text.substring(start, pos);
      // no fraction/exponent: parse as long first so full int64 range
      // survives (falls back to double on overflow)
      if (token.indexOf('.') < 0 && token.indexOf('e') < 0
          && token.indexOf('E') < 0) {
        try {
          return Json.of(Long.parseLong(token));
        } catch (NumberFormatException ignored) {
          // out of long range: fall through to double
        }
      }
      try {
        return Json.of(Double.parseDouble(token));
      } catch (NumberFormatException e) {
        throw new InferenceServerException("bad JSON number at " + start);
      }
    }
  }
}
