// In-process embedded inference server for Java hosts.
//
// Parity role: the reference's java-api-bindings (JavaCPP over the
// tritonserver C API — reference:
// src/java-api-bindings/scripts/install_dependencies_and_build.sh). Here
// the C API is native/include/client_tpu/server_embed.h
// (libclient_tpu_embed.so hosts the Python ServerCore + JAX inside this
// process), and the binding uses the JDK-22 Foreign Function & Memory API
// instead of JavaCPP/JNI — no codegen, no extra dependency.
//
// Requests and responses cross the boundary as the KServe v2 two-part
// HTTP body (JSON header + binary tails), the same bytes
// client_tpu.InferenceServerClient builds — so InferInput/InferResult
// marshaling is reusable verbatim on top of this class.
//
// Usage:
//   try (EmbeddedServer server =
//            EmbeddedServer.create("/path/to/repo", "{\"models\":[\"simple\"]}")) {
//     byte[] response = server.infer("simple", "", body, headerLen);
//     String meta = server.modelMetadata("simple");
//   }

package client_tpu.embed;

import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.foreign.ValueLayout;
import java.lang.invoke.MethodHandle;

public final class EmbeddedServer implements AutoCloseable {

  private static final Linker LINKER = Linker.nativeLinker();
  private static final SymbolLookup LIB =
      SymbolLookup.libraryLookup("libclient_tpu_embed.so", Arena.global());

  private static MethodHandle handle(String name, FunctionDescriptor desc) {
    return LINKER.downcallHandle(
        LIB.find(name).orElseThrow(
            () -> new UnsatisfiedLinkError("missing symbol " + name)),
        desc);
  }

  private static final MethodHandle INIT = handle(
      "ctpu_embed_init",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.ADDRESS,
          ValueLayout.ADDRESS));
  private static final MethodHandle CREATE = handle(
      "ctpu_embed_server_create",
      FunctionDescriptor.of(ValueLayout.JAVA_LONG, ValueLayout.ADDRESS,
          ValueLayout.ADDRESS));
  private static final MethodHandle INFER = handle(
      "ctpu_embed_infer",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS, ValueLayout.ADDRESS,
          ValueLayout.JAVA_LONG, ValueLayout.JAVA_LONG, ValueLayout.ADDRESS,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle METADATA = handle(
      "ctpu_embed_metadata",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle REPOSITORY_INDEX = handle(
      "ctpu_embed_repository_index",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle STATISTICS = handle(
      "ctpu_embed_statistics",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle LOAD = handle(
      "ctpu_embed_load_model",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle UNLOAD = handle(
      "ctpu_embed_unload_model",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle START_HTTP = handle(
      "ctpu_embed_start_http",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS, ValueLayout.ADDRESS));
  private static final MethodHandle DESTROY = handle(
      "ctpu_embed_server_destroy",
      FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.JAVA_LONG,
          ValueLayout.ADDRESS));
  private static final MethodHandle FREE = handle(
      "ctpu_embed_free",
      FunctionDescriptor.ofVoid(ValueLayout.ADDRESS));

  private final long server;
  private boolean closed;

  private EmbeddedServer(long server) {
    this.server = server;
  }

  /** Reads *error (char**), frees it, and throws when set. */
  private static void throwIfError(int rc, MemorySegment errorOut)
      throws EmbeddedServerException {
    if (rc == 0) {
      return;
    }
    MemorySegment message = errorOut.get(ValueLayout.ADDRESS, 0);
    String text = "native call failed";
    if (!MemorySegment.NULL.equals(message)) {
      text = message.reinterpret(Long.MAX_VALUE).getString(0);
      try {
        FREE.invokeExact(message);
      } catch (Throwable ignored) {
        // freeing the error string is best-effort
      }
    }
    throw new EmbeddedServerException(text);
  }

  /**
   * Initialize the embedded interpreter and create a server.
   *
   * @param repoPath path to the client_tpu checkout/install (null when
   *     importable from the environment)
   * @param optionsJson e.g. {"models": ["simple"]}; empty = full zoo
   */
  public static EmbeddedServer create(String repoPath, String optionsJson)
      throws EmbeddedServerException {
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      MemorySegment repo = repoPath == null
          ? MemorySegment.NULL : arena.allocateFrom(repoPath);
      int rc = (int) INIT.invokeExact(repo, errorOut);
      throwIfError(rc, errorOut);
      MemorySegment options = arena.allocateFrom(
          optionsJson == null ? "" : optionsJson);
      long server = (long) CREATE.invokeExact(options, errorOut);
      if (server == 0) {
        throwIfError(1, errorOut);
      }
      return new EmbeddedServer(server);
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  /**
   * One inference in the v2 two-part body format; returns the full
   * response body. The response header length (byte offset where binary
   * tails start; -1 = pure JSON) is returned via responseHeaderLen[0].
   */
  public byte[] infer(String modelName, String modelVersion, byte[] body,
      long headerLength, long[] responseHeaderLen)
      throws EmbeddedServerException {
    checkOpen();
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      MemorySegment bodySeg = arena.allocate(body.length);
      MemorySegment.copy(body, 0, bodySeg, ValueLayout.JAVA_BYTE, 0,
          body.length);
      MemorySegment responseOut = arena.allocate(ValueLayout.ADDRESS);
      MemorySegment lenOut = arena.allocate(ValueLayout.JAVA_LONG);
      MemorySegment headerOut = arena.allocate(ValueLayout.JAVA_LONG);
      int rc = (int) INFER.invokeExact(server,
          arena.allocateFrom(modelName),
          arena.allocateFrom(modelVersion == null ? "" : modelVersion),
          bodySeg, (long) body.length, headerLength,
          responseOut, lenOut, headerOut, errorOut);
      throwIfError(rc, errorOut);
      MemorySegment data = responseOut.get(ValueLayout.ADDRESS, 0);
      long len = lenOut.get(ValueLayout.JAVA_LONG, 0);
      byte[] response = new byte[(int) len];
      MemorySegment.copy(data.reinterpret(len), ValueLayout.JAVA_BYTE, 0,
          response, 0, (int) len);
      FREE.invokeExact(data);
      if (responseHeaderLen != null && responseHeaderLen.length > 0) {
        responseHeaderLen[0] = headerOut.get(ValueLayout.JAVA_LONG, 0);
      }
      return response;
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  private String jsonCall(MethodHandle method, String arg)
      throws EmbeddedServerException {
    checkOpen();
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      MemorySegment jsonOut = arena.allocate(ValueLayout.ADDRESS);
      int rc = arg == null
          ? (int) method.invokeExact(server, jsonOut, errorOut)
          : (int) method.invokeExact(server, arena.allocateFrom(arg),
              jsonOut, errorOut);
      throwIfError(rc, errorOut);
      MemorySegment data = jsonOut.get(ValueLayout.ADDRESS, 0);
      String json = data.reinterpret(Long.MAX_VALUE).getString(0);
      FREE.invokeExact(data);
      return json;
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  public String serverMetadata() throws EmbeddedServerException {
    return jsonCall(METADATA, "");
  }

  public String modelMetadata(String modelName)
      throws EmbeddedServerException {
    return jsonCall(METADATA, modelName);
  }

  public String repositoryIndex() throws EmbeddedServerException {
    return jsonCall(REPOSITORY_INDEX, null);
  }

  public String statistics(String modelName) throws EmbeddedServerException {
    return jsonCall(STATISTICS, modelName == null ? "" : modelName);
  }

  public void loadModel(String modelName, String configJson)
      throws EmbeddedServerException {
    checkOpen();
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      int rc = (int) LOAD.invokeExact(server, arena.allocateFrom(modelName),
          arena.allocateFrom(configJson == null ? "" : configJson), errorOut);
      throwIfError(rc, errorOut);
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  public void unloadModel(String modelName) throws EmbeddedServerException {
    checkOpen();
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      int rc = (int) UNLOAD.invokeExact(server,
          arena.allocateFrom(modelName), errorOut);
      throwIfError(rc, errorOut);
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  /** Also expose the embedded core over HTTP; returns the bound port. */
  public int startHttp(int port) throws EmbeddedServerException {
    checkOpen();
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      MemorySegment portSeg = arena.allocate(ValueLayout.JAVA_INT);
      portSeg.set(ValueLayout.JAVA_INT, 0, port);
      int rc = (int) START_HTTP.invokeExact(server, portSeg, errorOut);
      throwIfError(rc, errorOut);
      return portSeg.get(ValueLayout.JAVA_INT, 0);
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  private void checkOpen() throws EmbeddedServerException {
    if (closed) {
      throw new EmbeddedServerException("server already closed");
    }
  }

  @Override
  public void close() throws EmbeddedServerException {
    if (closed) {
      return;
    }
    closed = true;
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment errorOut = arena.allocate(ValueLayout.ADDRESS);
      int rc = (int) DESTROY.invokeExact(server, errorOut);
      throwIfError(rc, errorOut);
    } catch (EmbeddedServerException e) {
      throw e;
    } catch (Throwable t) {
      throw new EmbeddedServerException("FFM invocation failed", t);
    }
  }

  /** Typed failure from the embedded server or the FFM boundary. */
  public static final class EmbeddedServerException extends Exception {
    public EmbeddedServerException(String message) {
      super(message);
    }

    public EmbeddedServerException(String message, Throwable cause) {
      super(message, cause);
    }
  }
}
