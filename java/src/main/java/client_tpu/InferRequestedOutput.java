package client_tpu;

/** A requested output: binary by default, optional classification top-k,
 * optional shared-memory placement (reference:
 * src/java/.../InferRequestedOutput.java). */
public class InferRequestedOutput {
  private final String name;
  private final int classCount;
  private boolean binaryData = true;
  private String sharedMemoryRegion;
  private long sharedMemoryByteSize;
  private long sharedMemoryOffset;

  public InferRequestedOutput(String name) { this(name, 0); }

  public InferRequestedOutput(String name, int classCount) {
    this.name = name;
    this.classCount = classCount;
  }

  public String getName() { return name; }

  public InferRequestedOutput setBinaryData(boolean binaryData) {
    this.binaryData = binaryData;
    return this;
  }

  public InferRequestedOutput setSharedMemory(
      String regionName, long byteSize, long offset) {
    this.sharedMemoryRegion = regionName;
    this.sharedMemoryByteSize = byteSize;
    this.sharedMemoryOffset = offset;
    return this;
  }

  Json descriptor() {
    Json out = Json.object();
    out.put("name", Json.of(name));
    Json params = Json.object();
    if (sharedMemoryRegion != null) {
      params.put("shared_memory_region", Json.of(sharedMemoryRegion));
      params.put("shared_memory_byte_size", Json.of(sharedMemoryByteSize));
      if (sharedMemoryOffset != 0) {
        params.put("shared_memory_offset", Json.of(sharedMemoryOffset));
      }
    } else {
      if (classCount > 0) {
        params.put("classification", Json.of((long) classCount));
      }
      params.put("binary_data", Json.of(binaryData));
    }
    out.put("parameters", params);
    return out;
  }
}
