package client_tpu;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;

/**
 * An input tensor: metadata + little-endian payload bytes, or a
 * shared-memory placement (reference: src/java/.../InferInput.java and
 * BinaryProtocol.java — re-designed around java.nio instead of manual
 * byte shuffling).
 */
public class InferInput {
  private final String name;
  private final long[] shape;
  private final DataType datatype;
  private byte[] data;
  private String sharedMemoryRegion;
  private long sharedMemoryByteSize;
  private long sharedMemoryOffset;

  public InferInput(String name, long[] shape, DataType datatype) {
    this.name = name;
    this.shape = shape.clone();
    this.datatype = datatype;
  }

  public String getName() { return name; }
  public long[] getShape() { return shape.clone(); }
  public DataType getDatatype() { return datatype; }
  public byte[] getData() { return data; }
  public boolean inSharedMemory() { return sharedMemoryRegion != null; }

  private ByteBuffer alloc(int elements, int elemSize) {
    return ByteBuffer.allocate(elements * elemSize)
        .order(ByteOrder.LITTLE_ENDIAN);
  }

  public InferInput setData(int[] values) {
    ByteBuffer buf = alloc(values.length, 4);
    for (int v : values) buf.putInt(v);
    this.data = buf.array();
    this.sharedMemoryRegion = null;
    return this;
  }

  public InferInput setData(long[] values) {
    ByteBuffer buf = alloc(values.length, 8);
    for (long v : values) buf.putLong(v);
    this.data = buf.array();
    this.sharedMemoryRegion = null;
    return this;
  }

  public InferInput setData(float[] values) {
    ByteBuffer buf = alloc(values.length, 4);
    for (float v : values) buf.putFloat(v);
    this.data = buf.array();
    this.sharedMemoryRegion = null;
    return this;
  }

  public InferInput setData(double[] values) {
    ByteBuffer buf = alloc(values.length, 8);
    for (double v : values) buf.putDouble(v);
    this.data = buf.array();
    this.sharedMemoryRegion = null;
    return this;
  }

  public InferInput setData(byte[] rawBytes) {
    this.data = rawBytes.clone();
    this.sharedMemoryRegion = null;
    return this;
  }

  public InferInput setData(boolean[] values) {
    byte[] out = new byte[values.length];
    for (int i = 0; i < values.length; i++) out[i] = (byte) (values[i] ? 1 : 0);
    this.data = out;
    this.sharedMemoryRegion = null;
    return this;
  }

  /** BYTES elements: each string serialized with a 4-byte LE length prefix
   * (the binary tensor extension's string wire format). */
  public InferInput setData(String[] values) {
    int total = 0;
    byte[][] encoded = new byte[values.length][];
    for (int i = 0; i < values.length; i++) {
      encoded[i] = values[i].getBytes(StandardCharsets.UTF_8);
      total += 4 + encoded[i].length;
    }
    ByteBuffer buf = ByteBuffer.allocate(total).order(ByteOrder.LITTLE_ENDIAN);
    for (byte[] e : encoded) {
      buf.putInt(e.length);
      buf.put(e);
    }
    this.data = buf.array();
    this.sharedMemoryRegion = null;
    return this;
  }

  /** Place this input in a registered shared-memory region: the request
   * then carries only the placement parameters, no tensor bytes. */
  public InferInput setSharedMemory(String regionName, long byteSize, long offset) {
    this.sharedMemoryRegion = regionName;
    this.sharedMemoryByteSize = byteSize;
    this.sharedMemoryOffset = offset;
    this.data = null;
    return this;
  }

  /** The JSON descriptor for the request header. */
  Json descriptor() {
    Json tensor = Json.object();
    tensor.put("name", Json.of(name));
    tensor.put("datatype", Json.of(datatype.name()));
    Json dims = Json.array();
    for (long d : shape) dims.append(Json.of(d));
    tensor.put("shape", dims);
    Json params = Json.object();
    if (inSharedMemory()) {
      params.put("shared_memory_region", Json.of(sharedMemoryRegion));
      params.put("shared_memory_byte_size", Json.of(sharedMemoryByteSize));
      if (sharedMemoryOffset != 0) {
        params.put("shared_memory_offset", Json.of(sharedMemoryOffset));
      }
    } else {
      params.put("binary_data_size", Json.of((long) (data == null ? 0 : data.length)));
    }
    tensor.put("parameters", params);
    return tensor;
  }
}
