package client_tpu;

/** Typed failure from the server or the transport (reference:
 * src/java/.../InferenceException). Carries the HTTP status when one
 * exists (0 for transport-level failures). */
public class InferenceServerException extends Exception {
  private final int status;

  public InferenceServerException(String message) { this(message, 0); }

  public InferenceServerException(String message, int status) {
    super(message);
    this.status = status;
  }

  public InferenceServerException(String message, Throwable cause) {
    super(message, cause);
    this.status = 0;
  }

  public int getStatus() { return status; }
}
