"""HTTP InferResult: split the JSON header from the binary tail, decode tensors.

Reference parity: http/_infer_result.py:54-210 (offset map over the binary
tail, ``as_numpy`` frombuffer+reshape). TPU-first addition: ``as_jax`` places
the decoded tensor on a jax device with a single async host->device transfer.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .._tensor import ArenaOutputsMixin
from ..integrity import IntegrityError
from ..utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)


class InferResult(ArenaOutputsMixin):
    """The result of an inference request over HTTP.

    Body decoding raises typed :class:`~client_tpu.integrity.IntegrityError`
    (status ``INTEGRITY_VIOLATION``) for malformed responses — torn or
    non-UTF-8 JSON headers, header-length claims exceeding the body,
    binary sizes overrunning the buffer — so a byzantine replica's torn
    bytes classify into the ``invalid`` fault domain exactly like the
    contract lies ``integrity.check_result`` catches post-parse. The
    decoder does not know its endpoint; the frontend stamps the url on
    via ``integrity.note_parse_violation``."""

    def __init__(self, response_body: bytes, header_length: Optional[int] = None):
        self._buffer = memoryview(response_body)
        if header_length is not None and header_length > len(response_body):
            raise IntegrityError(
                "malformed", "", "Inference-Header-Content-Length",
                f"<= {len(response_body)}", str(header_length),
            )
        try:
            if header_length is None:
                self._response: Dict[str, Any] = json.loads(response_body)
                self._binary_start = len(response_body)
            else:
                self._response = json.loads(bytes(self._buffer[:header_length]))
                self._binary_start = header_length
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
            raise IntegrityError(
                "malformed", "", "response header", "valid JSON", str(e),
            ) from e
        if not isinstance(self._response, dict):
            raise IntegrityError(
                "malformed", "", "response header", "a JSON object",
                type(self._response).__name__,
            )
        # Map output name -> (start, end) into the binary tail, walked in
        # output order using each output's binary_data_size parameter.
        self._offsets: Dict[str, Tuple[int, int]] = {}
        cursor = self._binary_start
        for output in self._response.get("outputs", []):
            if not isinstance(output, dict):
                raise IntegrityError(
                    "malformed", "", "outputs", "JSON objects",
                    type(output).__name__,
                )
            params = output.get("parameters", {})
            size = params.get("binary_data_size") \
                if isinstance(params, dict) else None
            if size is not None:
                if not isinstance(size, int) or isinstance(size, bool) or size < 0:
                    raise IntegrityError(
                        "payload_size", "", str(output.get("name")),
                        "a non-negative integer",
                        f"invalid binary_data_size {size!r}",
                    )
                if cursor + size > len(response_body):
                    raise IntegrityError(
                        "tail", "", str(output.get("name")),
                        f"{size} bytes within the body",
                        f"claim reaches beyond the body "
                        f"({len(response_body) - cursor} bytes remain)",
                    )
                name = output.get("name")
                if not isinstance(name, str) or not name:
                    raise IntegrityError(
                        "output_name", "", "outputs",
                        "a non-empty string name", repr(name),
                    )
                self._offsets[name] = (cursor, cursor + size)
                cursor += size

    @classmethod
    def from_response_body(
        cls, response_body: bytes, header_length: Optional[int] = None
    ) -> "InferResult":
        return cls(response_body, header_length)

    # -- accessors ---------------------------------------------------------
    def get_response(self) -> Dict[str, Any]:
        return self._response

    def get_response_header(self, name: str, default: Optional[str] = None) -> Optional[str]:
        """A transport response header (e.g. ORCA's ``endpoint-load-metrics``)."""
        headers = getattr(self, "_response_headers", None)
        if not headers:
            return default
        for key, value in headers.items():
            if key.lower() == name.lower():
                return value
        return default

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        for output in self._response.get("outputs", []):
            if output.get("name") == name:
                return output
        return None

    def _decode(self, output: Dict[str, Any]) -> Optional[np.ndarray]:
        # a corrupted-but-parseable header (fuzzers produce these by
        # flipping bytes inside valid JSON) must fail TYPED here, never
        # as KeyError/ValueError from the numpy plumbing below
        name = output.get("name")
        datatype = output.get("datatype")
        shape = output.get("shape")
        if not isinstance(datatype, str) or not isinstance(shape, list):
            raise IntegrityError(
                "malformed", "", f"output '{name}'",
                "datatype and shape fields", repr(sorted(output))[:120])
        params = output.get("parameters", {})
        if "shared_memory_region" in params:
            lease = self._arena_lease_for(name)
            if lease is not None:
                # arena fast path: a zero-copy view over the leased slab,
                # pinned by the lease (reading after its last release
                # raises arena.ArenaLeaseReleased)
                return lease.as_numpy(datatype, shape)
            return None  # contents live in the shared-memory region
        if name in self._offsets:
            start, end = self._offsets[name]
            raw = self._buffer[start:end]
            if datatype == "BYTES":
                arr = deserialize_bytes_tensor(raw)
            elif datatype == "BF16":
                arr = deserialize_bf16_tensor(raw)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise InferenceServerException(
                        f"unknown datatype '{datatype}' for output '{name}'"
                    )
                arr = np.frombuffer(raw, dtype=np_dtype)
            return self._reshape(arr, shape, name)
        if "data" in output:
            if datatype == "BYTES":
                arr = np.array(
                    [d.encode("utf-8") if isinstance(d, str) else d for d in output["data"]],
                    dtype=np.object_,
                )
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise InferenceServerException(
                        f"unknown datatype '{datatype}' for output '{name}'"
                    )
                try:
                    arr = np.array(output["data"], dtype=np_dtype)
                except (ValueError, TypeError, OverflowError) as e:
                    raise IntegrityError(
                        "malformed", "", f"output '{name}' data",
                        datatype, str(e)) from None
            return self._reshape(arr, shape, name)
        return None

    @staticmethod
    def _reshape(arr: np.ndarray, shape, name) -> np.ndarray:
        try:
            return arr.reshape(shape)
        except (ValueError, TypeError) as e:
            # element count vs claimed shape disagree: the header lied
            raise IntegrityError(
                "payload_size", "", f"output '{name}'",
                shape, f"{arr.size} elements ({e})") from None

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        """Decode output ``name`` as a numpy array (zero-copy for fixed-width
        binary outputs AND for arena-leased shared-memory outputs); None if
        the output lives in a non-arena shared-memory region."""
        output = self.get_output(name)
        if output is None:
            return None
        return self._decode(output)

    def as_jax(self, name: str, device=None):
        """Decode output ``name`` and place it on a jax device (async)."""
        arr = self.as_numpy(name)
        if arr is None:
            return None
        import jax

        if arr.dtype == np.object_:
            raise InferenceServerException("BYTES outputs cannot be placed on device")
        return jax.device_put(arr, device)
