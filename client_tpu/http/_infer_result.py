"""HTTP InferResult: split the JSON header from the binary tail, decode tensors.

Reference parity: http/_infer_result.py:54-210 (offset map over the binary
tail, ``as_numpy`` frombuffer+reshape). TPU-first addition: ``as_jax`` places
the decoded tensor on a jax device with a single async host->device transfer.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .._tensor import ArenaOutputsMixin
from ..utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)


class InferResult(ArenaOutputsMixin):
    """The result of an inference request over HTTP."""

    def __init__(self, response_body: bytes, header_length: Optional[int] = None):
        self._buffer = memoryview(response_body)
        if header_length is not None and header_length > len(response_body):
            raise InferenceServerException(
                f"malformed inference response: Inference-Header-Content-Length "
                f"{header_length} exceeds the {len(response_body)}-byte body"
            )
        try:
            if header_length is None:
                self._response: Dict[str, Any] = json.loads(response_body)
                self._binary_start = len(response_body)
            else:
                self._response = json.loads(bytes(self._buffer[:header_length]))
                self._binary_start = header_length
        except json.JSONDecodeError as e:
            raise InferenceServerException(
                f"malformed inference response: {e}"
            ) from e
        if not isinstance(self._response, dict):
            raise InferenceServerException(
                "malformed inference response: header is not a JSON object"
            )
        # Map output name -> (start, end) into the binary tail, walked in
        # output order using each output's binary_data_size parameter.
        self._offsets: Dict[str, Tuple[int, int]] = {}
        cursor = self._binary_start
        for output in self._response.get("outputs", []):
            params = output.get("parameters", {})
            size = params.get("binary_data_size")
            if size is not None:
                if not isinstance(size, int) or isinstance(size, bool) or size < 0:
                    raise InferenceServerException(
                        f"malformed inference response: output "
                        f"'{output.get('name')}' has invalid binary_data_size "
                        f"{size!r}"
                    )
                if cursor + size > len(response_body):
                    raise InferenceServerException(
                        f"malformed inference response: output "
                        f"'{output.get('name')}' declares {size} binary bytes "
                        "beyond the body"
                    )
                self._offsets[output["name"]] = (cursor, cursor + size)
                cursor += size

    @classmethod
    def from_response_body(
        cls, response_body: bytes, header_length: Optional[int] = None
    ) -> "InferResult":
        return cls(response_body, header_length)

    # -- accessors ---------------------------------------------------------
    def get_response(self) -> Dict[str, Any]:
        return self._response

    def get_response_header(self, name: str, default: Optional[str] = None) -> Optional[str]:
        """A transport response header (e.g. ORCA's ``endpoint-load-metrics``)."""
        headers = getattr(self, "_response_headers", None)
        if not headers:
            return default
        for key, value in headers.items():
            if key.lower() == name.lower():
                return value
        return default

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        for output in self._response.get("outputs", []):
            if output["name"] == name:
                return output
        return None

    def _decode(self, output: Dict[str, Any]) -> Optional[np.ndarray]:
        name = output["name"]
        datatype = output["datatype"]
        shape = output["shape"]
        params = output.get("parameters", {})
        if "shared_memory_region" in params:
            lease = self._arena_lease_for(name)
            if lease is not None:
                # arena fast path: a zero-copy view over the leased slab,
                # pinned by the lease (reading after its last release
                # raises arena.ArenaLeaseReleased)
                return lease.as_numpy(datatype, shape)
            return None  # contents live in the shared-memory region
        if name in self._offsets:
            start, end = self._offsets[name]
            raw = self._buffer[start:end]
            if datatype == "BYTES":
                arr = deserialize_bytes_tensor(raw)
            elif datatype == "BF16":
                arr = deserialize_bf16_tensor(raw)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise InferenceServerException(
                        f"unknown datatype '{datatype}' for output '{name}'"
                    )
                arr = np.frombuffer(raw, dtype=np_dtype)
            return arr.reshape(shape)
        if "data" in output:
            np_dtype = triton_to_np_dtype(datatype)
            if datatype == "BYTES":
                arr = np.array(
                    [d.encode("utf-8") if isinstance(d, str) else d for d in output["data"]],
                    dtype=np.object_,
                )
            else:
                arr = np.array(output["data"], dtype=np_dtype)
            return arr.reshape(shape)
        return None

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        """Decode output ``name`` as a numpy array (zero-copy for fixed-width
        binary outputs AND for arena-leased shared-memory outputs); None if
        the output lives in a non-arena shared-memory region."""
        output = self.get_output(name)
        if output is None:
            return None
        return self._decode(output)

    def as_jax(self, name: str, device=None):
        """Decode output ``name`` and place it on a jax device (async)."""
        arr = self.as_numpy(name)
        if arr is None:
            return None
        import jax

        if arr.dtype == np.object_:
            raise InferenceServerException("BYTES outputs cannot be placed on device")
        return jax.device_put(arr, device)
