"""KServe v2 HTTP/REST client namespace (mirrors ``tritonclient.http``)."""

from .._base import (
    BasicAuth,
    InferenceServerClientBase,
    InferenceServerClientPlugin,
    Request,
)
from .._tensor import InferInput, InferRequestedOutput
from ..utils import InferenceServerException
from ._client import InferAsyncRequest, InferenceServerClient
from ._infer_result import InferResult

__all__ = [
    "BasicAuth",
    "InferAsyncRequest",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferenceServerClient",
    "InferenceServerClientBase",
    "InferenceServerClientPlugin",
    "InferenceServerException",
    "Request",
]
