"""KServe v2 HTTP/REST request-body builder and response helpers.

Wire contract (identical to the reference so bodies interoperate with a real
tritonserver — reference http/_utils.py:90-151, http/_infer_result.py:54-106):

- Request body = UTF-8 JSON header, then the raw binary payloads of every
  input that staged binary data, concatenated in input order. When any binary
  payload is present the ``Inference-Header-Content-Length`` request header
  carries the JSON byte length.
- Response body = JSON header (+ binary tail located by the response's
  ``Inference-Header-Content-Length``), each binary output described by a
  ``binary_data_size`` parameter; outputs appear in the tail in order.
"""

from __future__ import annotations

import gzip
import json
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .._tensor import InferInput, InferRequestedOutput
from ..utils import RESERVED_REQUEST_PARAMETERS, InferenceServerException


def build_request_parameters(
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Validate custom parameters and assemble the request-level parameter bag."""
    out: Dict[str, Any] = {}
    if sequence_id:
        out["sequence_id"] = sequence_id
        out["sequence_start"] = sequence_start
        out["sequence_end"] = sequence_end
    if priority:
        out["priority"] = priority
    if timeout is not None:
        out["timeout"] = timeout
    if parameters:
        for key, value in parameters.items():
            if key in RESERVED_REQUEST_PARAMETERS:
                raise InferenceServerException(
                    f"parameter '{key}' is a reserved parameter and cannot be "
                    "specified as a custom parameter"
                )
            out[key] = value
    return (request_id if request_id else None), out


def build_infer_body(
    inputs: Sequence[InferInput],
    outputs: Optional[Sequence[InferRequestedOutput]] = None,
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> Tuple[bytes, Optional[int]]:
    """Build the two-part infer body.

    Returns ``(body, json_size)``; ``json_size`` is None when the body is pure
    JSON (no binary tensor payloads).
    """
    rid, params = build_request_parameters(
        request_id, sequence_id, sequence_start, sequence_end, priority, timeout, parameters
    )
    header: Dict[str, Any] = {}
    if rid is not None:
        header["id"] = rid

    if outputs:
        header["outputs"] = [o._get_tensor_json() for o in outputs]
    else:
        # No explicit outputs: ask the server to return everything as binary.
        params["binary_data_output"] = True

    if params:
        header["parameters"] = params

    header["inputs"] = [i._get_tensor_json() for i in inputs]

    json_bytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    chunks: List[bytes] = [json_bytes]
    has_binary = False
    for i in inputs:
        raw = i._get_binary_data()
        if raw is not None:
            has_binary = True
            chunks.append(raw if isinstance(raw, bytes) else bytes(raw))
    if not has_binary:
        return json_bytes, None
    return b"".join(chunks), len(json_bytes)


def compress_body(body: bytes, algorithm: Optional[str]) -> Tuple[bytes, Optional[str]]:
    """Compress a request body; returns (body, Content-Encoding header value)."""
    if algorithm is None or algorithm == "none":
        return body, None
    if algorithm == "gzip":
        return gzip.compress(body), "gzip"
    if algorithm == "deflate":
        return zlib.compress(body), "deflate"
    raise InferenceServerException(f"unsupported compression algorithm '{algorithm}'")


def decompress_body(body: bytes, content_encoding: Optional[str]) -> bytes:
    if not content_encoding or content_encoding == "identity":
        return body
    if content_encoding == "gzip":
        return gzip.decompress(body)
    if content_encoding == "deflate":
        return zlib.decompress(body)
    raise InferenceServerException(
        f"unsupported response Content-Encoding '{content_encoding}'"
    )


def raise_if_error(status: int, body: bytes) -> None:
    """Raise InferenceServerException for HTTP error statuses.

    The server reports errors as ``{"error": msg}``; tolerate non-JSON bodies.
    """
    if status < 400:
        return
    msg = None
    try:
        parsed = json.loads(body)
        if isinstance(parsed, dict):
            msg = parsed.get("error")
    except Exception:
        pass
    if msg is None:
        msg = body.decode("utf-8", errors="replace") if body else f"HTTP {status}"
    raise InferenceServerException(msg=msg, status=str(status))


class SSEDecoder:
    """Incremental SSE event-stream decoder shared by the sync and aio
    generate_stream clients (so framing behavior cannot drift between them).

    Spec-compliant framing: events end at a blank line under LF *or* CRLF
    framing (``\\r?\\n\\r?\\n``), and multiple ``data:`` lines within one
    event are joined with ``\\n`` per the SSE spec before parsing. Events
    are size-unbounded (the buffer grows to the event) — large streamed
    tensors must not hit a line-length ceiling. ``feed`` returns the
    ``data`` payload of each event completed by the chunk; ``flush``
    drains a final event whose terminating blank line never arrived
    (server closed after a partial flush).
    """

    __slots__ = ("_buf", "_scan")

    def __init__(self):
        self._buf = b""
        self._scan = 0  # resume boundary search here (avoid re-scanning)

    @staticmethod
    def _event_payload(raw: bytes) -> Optional[bytes]:
        datas = []
        for line in raw.split(b"\n"):
            line = line.rstrip(b"\r")
            if line.startswith(b"data:"):
                value = line[len(b"data:"):]
                if value.startswith(b" "):  # spec: strip ONE leading space
                    value = value[1:]
                datas.append(value.strip())
        if not datas:
            return None
        return b"\n".join(datas)

    def feed(self, chunk: bytes) -> List[bytes]:
        self._buf += chunk
        payloads: List[bytes] = []
        while True:
            # find the earliest \n\n / \r\n\r\n / \n\r\n / \r\n\n boundary
            i = self._buf.find(b"\n", self._scan)
            boundary = None
            while i != -1:
                rest = self._buf[i + 1:i + 3]
                if rest.startswith(b"\n"):
                    boundary = (i, i + 2)
                    break
                if rest.startswith(b"\r\n"):
                    boundary = (i, i + 3)
                    break
                if rest in (b"", b"\r"):
                    # possible boundary split across chunks: wait for more
                    break
                i = self._buf.find(b"\n", i + 1)
            if boundary is None:
                # nothing conclusive: resume next feed just before the tail
                # (a boundary can span at most 3 trailing bytes)
                self._scan = max(0, len(self._buf) - 3)
                return payloads
            end, nxt = boundary
            raw, self._buf = self._buf[:end], self._buf[nxt:]
            self._scan = 0
            payload = self._event_payload(raw)
            if payload is not None:
                payloads.append(payload)

    def flush(self) -> List[bytes]:
        """Parse a final unterminated event; must not silently drop it."""
        raw, self._buf, self._scan = self._buf, b"", 0
        payload = self._event_payload(raw)
        return [payload] if payload is not None else []


def parse_sse_event(payload: bytes):
    """Decode one generate-extension SSE ``data:`` payload.

    Shared by the sync and aio clients so hostile-input handling cannot
    drift between them: non-JSON and JSON-but-not-an-object payloads raise
    the typed client exception, and an in-band ``{"error": msg}`` event
    raises with the server's message.
    """
    try:
        event = json.loads(payload)
    except ValueError as e:
        raise InferenceServerException(
            f"malformed generate_stream event: {payload[:120]!r}") from e
    if not isinstance(event, dict):
        raise InferenceServerException(
            f"malformed generate_stream event (not an object): "
            f"{payload[:120]!r}")
    if set(event) == {"error"}:
        raise InferenceServerException(event["error"])
    return event
