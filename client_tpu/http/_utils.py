"""KServe v2 HTTP/REST request-body builder and response helpers.

Wire contract (identical to the reference so bodies interoperate with a real
tritonserver — reference http/_utils.py:90-151, http/_infer_result.py:54-106):

- Request body = UTF-8 JSON header, then the raw binary payloads of every
  input that staged binary data, concatenated in input order. When any binary
  payload is present the ``Inference-Header-Content-Length`` request header
  carries the JSON byte length.
- Response body = JSON header (+ binary tail located by the response's
  ``Inference-Header-Content-Length``), each binary output described by a
  ``binary_data_size`` parameter; outputs appear in the tail in order.
"""

from __future__ import annotations

import gzip
import json
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .._tensor import InferInput, InferRequestedOutput
from ..utils import RESERVED_REQUEST_PARAMETERS, InferenceServerException


def build_request_parameters(
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> Tuple[Optional[str], Dict[str, Any]]:
    """Validate custom parameters and assemble the request-level parameter bag."""
    out: Dict[str, Any] = {}
    if sequence_id:
        out["sequence_id"] = sequence_id
        out["sequence_start"] = sequence_start
        out["sequence_end"] = sequence_end
    if priority:
        out["priority"] = priority
    if timeout is not None:
        out["timeout"] = timeout
    if parameters:
        for key, value in parameters.items():
            if key in RESERVED_REQUEST_PARAMETERS:
                raise InferenceServerException(
                    f"parameter '{key}' is a reserved parameter and cannot be "
                    "specified as a custom parameter"
                )
            out[key] = value
    return (request_id if request_id else None), out


def build_infer_body(
    inputs: Sequence[InferInput],
    outputs: Optional[Sequence[InferRequestedOutput]] = None,
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> Tuple[bytes, Optional[int]]:
    """Build the two-part infer body.

    Returns ``(body, json_size)``; ``json_size`` is None when the body is pure
    JSON (no binary tensor payloads).
    """
    rid, params = build_request_parameters(
        request_id, sequence_id, sequence_start, sequence_end, priority, timeout, parameters
    )
    header: Dict[str, Any] = {}
    if rid is not None:
        header["id"] = rid

    if outputs:
        header["outputs"] = [o._get_tensor_json() for o in outputs]
    else:
        # No explicit outputs: ask the server to return everything as binary.
        params["binary_data_output"] = True

    if params:
        header["parameters"] = params

    header["inputs"] = [i._get_tensor_json() for i in inputs]

    json_bytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    chunks: List[bytes] = [json_bytes]
    has_binary = False
    for i in inputs:
        raw = i._get_binary_data()
        if raw is not None:
            has_binary = True
            chunks.append(raw if isinstance(raw, bytes) else bytes(raw))
    if not has_binary:
        return json_bytes, None
    return b"".join(chunks), len(json_bytes)


def compress_body(body: bytes, algorithm: Optional[str]) -> Tuple[bytes, Optional[str]]:
    """Compress a request body; returns (body, Content-Encoding header value)."""
    if algorithm is None or algorithm == "none":
        return body, None
    if algorithm == "gzip":
        return gzip.compress(body), "gzip"
    if algorithm == "deflate":
        return zlib.compress(body), "deflate"
    raise InferenceServerException(f"unsupported compression algorithm '{algorithm}'")


def decompress_body(body: bytes, content_encoding: Optional[str]) -> bytes:
    if not content_encoding or content_encoding == "identity":
        return body
    if content_encoding == "gzip":
        return gzip.decompress(body)
    if content_encoding == "deflate":
        return zlib.decompress(body)
    raise InferenceServerException(
        f"unsupported response Content-Encoding '{content_encoding}'"
    )


def raise_if_error(status: int, body: bytes) -> None:
    """Raise InferenceServerException for HTTP error statuses.

    The server reports errors as ``{"error": msg}``; tolerate non-JSON bodies.
    """
    if status < 400:
        return
    msg = None
    try:
        parsed = json.loads(body)
        if isinstance(parsed, dict):
            msg = parsed.get("error")
    except Exception:
        pass
    if msg is None:
        msg = body.decode("utf-8", errors="replace") if body else f"HTTP {status}"
    raise InferenceServerException(msg=msg, status=str(status))


def parse_sse_event(payload: bytes):
    """Decode one generate-extension SSE ``data:`` payload.

    Shared by the sync and aio clients so hostile-input handling cannot
    drift between them: non-JSON and JSON-but-not-an-object payloads raise
    the typed client exception, and an in-band ``{"error": msg}`` event
    raises with the server's message.
    """
    try:
        event = json.loads(payload)
    except ValueError as e:
        raise InferenceServerException(
            f"malformed generate_stream event: {payload[:120]!r}") from e
    if not isinstance(event, dict):
        raise InferenceServerException(
            f"malformed generate_stream event (not an object): "
            f"{payload[:120]!r}")
    if set(event) == {"error"}:
        raise InferenceServerException(event["error"])
    return event
