"""Auth plugins for the sync client (reference: */auth subpackage).

Plugins are transport-agnostic — BasicAuth from the shared base; this
module mirrors the reference import path.
"""

from ..._base import BasicAuth, InferenceServerClientPlugin

__all__ = ["BasicAuth", "InferenceServerClientPlugin"]
