"""Asyncio KServe v2 HTTP client (mirrors ``tritonclient.http.aio``).

The aiohttp re-implementation of the full HTTP surface with ``async def``
methods (reference: http/aio/__init__.py:92-775). Shares the body
builders/parsers and value model with the sync client — only the transport
differs.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, Sequence
from urllib.parse import quote

import aiohttp

from ..._base import SHM_FAMILY_OF, InferenceServerClientBase, Request
from ..._tensor import InferInput, InferRequestedOutput
from ...observe import TRACEPARENT_HEADER
from ...resilience import (
    FATAL,
    RETRYABLE_HTTP_STATUSES,
    AttemptBudget,
    RetryableStatusError,
    classify_fault,
)
from ...integrity import IntegrityError
from ...utils import InferenceServerException
from .._client import InferenceServerClient as _SyncClient
from .._infer_result import InferResult
from .._utils import (
    SSEDecoder,
    build_infer_body,
    compress_body,
    parse_sse_event,
    raise_if_error,
)

__all__ = [
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferenceServerClient",
]


class InferenceServerClient(InferenceServerClientBase):
    """Asyncio client for the KServe v2 HTTP/REST protocol."""

    _FRONTEND = "http_aio"
    _BATCH_AIO = True

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        conn_limit: int = 100,
        conn_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
    ):
        super().__init__()
        if "://" in url:
            raise InferenceServerException(
                f"unexpected scheme in url '{url}' (pass host:port; use ssl=True for https)"
            )
        scheme = "https" if ssl else "http"
        self._url = url
        self._base = f"{scheme}://{url}"
        self._verbose = verbose
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=conn_limit, ssl=ssl_context),
            timeout=aiohttp.ClientTimeout(total=conn_timeout),
        )

    async def close(self) -> None:
        await self._session.close()

    async def __aenter__(self) -> "InferenceServerClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- transport ---------------------------------------------------------
    async def _request(
        self, method: str, path: str, body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
        idempotent: bool = True,
        resilience=None,
        span=None,
    ):
        """One HTTP round trip under the client's resilience policy (same
        idempotency contract as the sync twin: in-flight failures and
        shed-load statuses re-attempt only for idempotent requests)."""
        url = f"{self._base}/{path}"
        policy = self._resilience_for(resilience)
        kwargs: Dict[str, Any] = dict(params=query_params)
        if body is not None:
            kwargs["data"] = body
        budget = AttemptBudget(policy, timeout)
        retry_statuses = policy is not None and policy.retry_http_statuses

        async def attempt():
            # plugin runs per attempt: a token-refreshing plugin must be
            # able to stamp a FRESH credential on every retry
            request = Request(dict(headers or {}))
            self._call_plugin(request)
            kwargs["headers"] = request.headers
            if self._verbose:
                print(f"{method} {url}, headers {request.headers}")
            remaining = budget.attempt_timeout_s(status="499")
            if remaining is not None:
                kwargs["timeout"] = aiohttp.ClientTimeout(total=remaining)
            try:
                t_send = time.perf_counter_ns() if span is not None else 0
                async with self._session.request(method, url, **kwargs) as resp:
                    if span is not None:
                        # headers arrived: request issue -> first byte
                        t_recv = time.perf_counter_ns()
                        span.phase("ttfb", t_send, t_recv)
                    data = await resp.read()
                    if span is not None:
                        span.phase("recv", t_recv, time.perf_counter_ns())
                    if self._verbose:
                        print(f"-> {resp.status}")
                    out = resp.status, dict(resp.headers), data
            except (TimeoutError, asyncio.TimeoutError) as e:
                # aiohttp raises TimeoutError on ClientTimeout(total=) expiry
                # (asyncio.TimeoutError is a distinct class before 3.11)
                raise InferenceServerException(
                    "Deadline Exceeded", status="499") from e
            except aiohttp.ClientError as e:
                raise InferenceServerException(f"connection error: {e}") from e
            if retry_statuses and str(out[0]) in RETRYABLE_HTTP_STATUSES:
                raise RetryableStatusError(out[0], out)
            return out

        run_attempt = attempt
        if span is not None:
            async def run_attempt():
                t_a = time.perf_counter_ns()
                try:
                    return await attempt()
                finally:
                    span.phase("attempt", t_a, time.perf_counter_ns())

        if policy is None:
            return await run_attempt()
        on_retry = None
        if span is not None:
            def on_retry(n, exc, delay):
                span.event("retry", attempt=n, backoff_s=round(delay, 6),
                           error=type(exc).__name__)
        try:
            return await policy.execute_async(
                run_attempt, idempotent=idempotent, timeout_s=timeout,
                on_retry=on_retry)
        except RetryableStatusError as e:
            return e.response

    async def _get_json(self, path, headers=None, query_params=None):
        status, _, data = await self._request("GET", path, None, headers, query_params)
        raise_if_error(status, data)
        return json.loads(data) if data else {}

    async def _post_json(self, path, body, headers=None, query_params=None):
        status, _, data = await self._request("POST", path, body, headers, query_params)
        raise_if_error(status, data)
        return json.loads(data) if data else {}

    # -- health / metadata -------------------------------------------------
    async def _health(self, path, headers, query_params, probe: bool,
                      client_timeout: Optional[float]) -> bool:
        """Shared live/ready GET; same contract as the sync twin: transport
        failures raise by default, ``probe=True`` maps connect/transient/
        timeout-class failures to False and bypasses the resilience policy
        (health pollers must observe the endpoint, not an open breaker)."""
        try:
            status, _, _ = await self._request(
                "GET", path, None, headers, query_params,
                timeout=client_timeout,
                resilience=False if probe else None,
            )
        except InferenceServerException as e:
            if probe and classify_fault(e) != FATAL:
                return False
            raise
        return status == 200

    async def is_server_live(self, headers=None, query_params=None,
                             probe: bool = False,
                             client_timeout: Optional[float] = None) -> bool:
        return await self._health(
            "v2/health/live", headers, query_params, probe, client_timeout)

    async def is_server_ready(self, headers=None, query_params=None,
                              probe: bool = False,
                              client_timeout: Optional[float] = None) -> bool:
        return await self._health(
            "v2/health/ready", headers, query_params, probe, client_timeout)

    async def is_model_ready(self, model_name, model_version="", headers=None, query_params=None) -> bool:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        status, _, _ = await self._request("GET", path + "/ready", None, headers, query_params)
        return status == 200

    async def get_server_metadata(self, headers=None, query_params=None):
        return await self._get_json("v2", headers, query_params)

    async def get_model_metadata(self, model_name, model_version="", headers=None, query_params=None):
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        metadata = await self._get_json(path, headers, query_params)
        # captured into the integrity contract cache: later responses
        # are validated against this fetched truth (never vice versa)
        self._integrity_note_metadata(model_name, metadata)
        return metadata

    async def get_model_config(self, model_name, model_version="", headers=None, query_params=None):
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return await self._get_json(path + "/config", headers, query_params)

    # -- repository / stats / settings --------------------------------------
    async def get_model_repository_index(self, headers=None, query_params=None):
        status, _, data = await self._request("POST", "v2/repository/index", b"", headers, query_params)
        raise_if_error(status, data)
        return json.loads(data) if data else []

    async def load_model(self, model_name, headers=None, query_params=None, config=None, files=None):
        import base64

        params: Dict[str, Any] = {}
        if config is not None:
            params["config"] = config
        for p, content in (files or {}).items():
            params[p] = base64.b64encode(content).decode("ascii")
        body = json.dumps({"parameters": params} if params else {}).encode()
        await self._post_json(f"v2/repository/models/{quote(model_name)}/load", body, headers, query_params)

    async def unload_model(self, model_name, headers=None, query_params=None, unload_dependents=False):
        body = json.dumps({"parameters": {"unload_dependents": unload_dependents}}).encode()
        await self._post_json(f"v2/repository/models/{quote(model_name)}/unload", body, headers, query_params)

    async def get_inference_statistics(self, model_name="", model_version="", headers=None, query_params=None):
        if model_name:
            path = f"v2/models/{quote(model_name)}"
            if model_version:
                path += f"/versions/{model_version}"
            path += "/stats"
        else:
            path = "v2/models/stats"
        return await self._get_json(path, headers, query_params)

    async def update_trace_settings(self, model_name=None, settings=None, headers=None, query_params=None):
        path = f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        return await self._post_json(path, json.dumps(settings or {}).encode(), headers, query_params)

    async def get_trace_settings(self, model_name=None, headers=None, query_params=None):
        path = f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        return await self._get_json(path, headers, query_params)

    async def update_log_settings(self, settings, headers=None, query_params=None):
        return await self._post_json("v2/logging", json.dumps(settings).encode(), headers, query_params)

    async def get_log_settings(self, headers=None, query_params=None):
        return await self._get_json("v2/logging", headers, query_params)

    # -- shared memory -----------------------------------------------------
    async def _shm_status(self, family, region_name, headers, query_params):
        path = f"v2/{family}"
        if region_name:
            path += f"/region/{quote(region_name)}"
        status, _, data = await self._request("GET", path + "/status", None, headers, query_params)
        raise_if_error(status, data)
        return json.loads(data) if data else []

    async def _shm_unregister(self, family, name, headers, query_params):
        async def call():
            path = f"v2/{family}"
            if name:
                path += f"/region/{quote(name)}"
            await self._post_json(
                path + "/unregister", b"", headers, query_params)

        await self._shm_call_async(SHM_FAMILY_OF[family], "unregister", call,
                                   region_name=name)

    async def get_system_shared_memory_status(self, region_name="", headers=None, query_params=None):
        return await self._shm_status("systemsharedmemory", region_name, headers, query_params)

    async def register_system_shared_memory(self, name, key, byte_size, offset=0, headers=None, query_params=None):
        async def call():
            body = json.dumps(
                {"key": key, "offset": offset, "byte_size": byte_size}
            ).encode()
            await self._post_json(
                f"v2/systemsharedmemory/region/{quote(name)}/register",
                body, headers, query_params)

        await self._shm_call_async("system", "register", call)

    async def unregister_system_shared_memory(self, name="", headers=None, query_params=None):
        await self._shm_unregister("systemsharedmemory", name, headers, query_params)

    async def _shm_register_handle(self, family, name, raw_handle, device_id, byte_size, headers, query_params):
        async def call():
            body = json.dumps(
                {"raw_handle": {"b64": raw_handle}, "device_id": device_id,
                 "byte_size": byte_size}
            ).encode()
            await self._post_json(
                f"v2/{family}/region/{quote(name)}/register",
                body, headers, query_params)

        await self._shm_call_async(SHM_FAMILY_OF[family], "register", call)

    async def get_cuda_shared_memory_status(self, region_name="", headers=None, query_params=None):
        return await self._shm_status("cudasharedmemory", region_name, headers, query_params)

    async def register_cuda_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, query_params=None):
        await self._shm_register_handle("cudasharedmemory", name, raw_handle, device_id, byte_size, headers, query_params)

    async def unregister_cuda_shared_memory(self, name="", headers=None, query_params=None):
        await self._shm_unregister("cudasharedmemory", name, headers, query_params)

    async def get_tpu_shared_memory_status(self, region_name="", headers=None, query_params=None):
        return await self._shm_status("tpusharedmemory", region_name, headers, query_params)

    async def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, query_params=None):
        await self._shm_register_handle("tpusharedmemory", name, raw_handle, device_id, byte_size, headers, query_params)

    async def unregister_tpu_shared_memory(self, name="", headers=None, query_params=None):
        await self._shm_unregister("tpusharedmemory", name, headers, query_params)

    # -- inference ---------------------------------------------------------
    # offline marshaling statics (same behavior as the sync client's —
    # reference http/aio/__init__.py exposes them on the aio class too)
    generate_request_body = staticmethod(_SyncClient.generate_request_body)
    parse_response_body = staticmethod(_SyncClient.parse_response_body)

    async def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        request_compression_algorithm: Optional[str] = None,
        response_compression_algorithm: Optional[str] = None,
        parameters: Optional[Dict[str, Any]] = None,
        resilience=None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        span = self._obs_begin(self._FRONTEND, model_name)
        if span is not None and tenant is not None:
            # client-side QoS attribution only (see client_tpu.tenancy);
            # the tenant is never sent on the wire
            span.event("tenant", tenant=tenant)
        actx = None
        try:
            # arena data plane: promote staged binary inputs into leased
            # slabs and ensure (cached) region registrations BEFORE the
            # body is built, so the request rides shm params
            actx = await self._arena_bind_async(inputs, outputs)
            body, json_size = build_infer_body(
                inputs, outputs, request_id, sequence_id, sequence_start,
                sequence_end, priority, timeout, parameters,
            )
            hdrs = self._orca_opt_in(dict(headers or {}))
            body, encoding = compress_body(body, request_compression_algorithm)
            if encoding:
                hdrs["Content-Encoding"] = encoding
            if response_compression_algorithm in ("gzip", "deflate"):
                hdrs["Accept-Encoding"] = response_compression_algorithm
            if json_size is not None:
                hdrs["Inference-Header-Content-Length"] = str(json_size)
                hdrs["Content-Type"] = "application/octet-stream"
            else:
                hdrs["Content-Type"] = "application/json"
            if span is not None:
                hdrs[TRACEPARENT_HEADER] = span.traceparent()
                span.phase("serialize", span.start_ns, time.perf_counter_ns())
            uri = f"v2/models/{quote(model_name)}"
            if model_version:
                uri += f"/versions/{model_version}"
            status, resp_headers, data = await self._request(
                "POST", uri + "/infer", body, hdrs, query_params,
                timeout=client_timeout, idempotent=sequence_id == 0,
                resilience=resilience, span=span,
            )
            raise_if_error(status, data)  # aiohttp auto-decodes Content-Encoding
            t_deser = time.perf_counter_ns() if span is not None else 0
            header_length = resp_headers.get("Inference-Header-Content-Length")
            try:
                result = InferResult.from_response_body(
                    data,
                    int(header_length) if header_length is not None else None,
                )
            except IntegrityError as e:
                # undecodable body (torn JSON, overrun binary sizes):
                # attribute to this endpoint and account like any other
                # integrity violation, then let it classify as INVALID
                self._integrity_parse_note(e)
                raise
            result._response_headers = resp_headers  # e.g. endpoint-load-metrics
            if actx is not None:
                actx.finish(result)
            # contract validation: the result never reaches the caller
            # (nor the ORCA/verbose paths below) un-checked
            self._integrity_check(result, inputs, outputs, request_id,
                                  model_name)
        except BaseException as e:
            if span is not None:
                self._telemetry.finish(span, error=e)
            raise
        finally:
            if actx is not None:
                actx.settle()
        if span is not None:
            span.phase("deserialize", t_deser, time.perf_counter_ns())
            self._telemetry.finish(span)
        # after the phase capture: ORCA bookkeeping (header parse + gauge
        # writes) must not masquerade as deserialize milliseconds
        self._orca_ingest(result)
        if self._verbose:
            print(result.get_response())
        return result

    # -- generate extension (LLM JSON API) ----------------------------------
    # Server counterpart: the generate routes on both HTTP frontends
    # (reference protocol: tritonserver extension_generate — flat JSON keys
    # map to input tensors; streaming responses arrive as SSE). Path and
    # payload builders are the sync client's (same sharing pattern as
    # generate_request_body above).
    _generate_path = staticmethod(_SyncClient._generate_path)
    _generate_payload = staticmethod(_SyncClient._generate_payload)

    async def generate(
        self,
        model_name: str,
        inputs: Dict[str, Any],
        model_version: str = "",
        request_id: str = "",
        parameters: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """One-shot generate: flat JSON in, flat JSON out (the model must
        produce exactly one response; decoupled many-response models need
        :meth:`generate_stream`)."""
        return await self._post_json(
            self._generate_path(model_name, model_version, stream=False),
            self._generate_payload(inputs, request_id, parameters),
            headers, query_params,
        )

    async def generate_stream(
        self,
        model_name: str,
        inputs: Dict[str, Any],
        model_version: str = "",
        request_id: str = "",
        parameters: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
    ):
        """Async iterator over generate-extension SSE events, one dict per
        streamed response. Abandoning the iterator mid-stream closes the
        connection, which the server accounts as a client cancel (the
        cancel stats bucket), not a success. In-band error events raise.

        With telemetry configured the stream is traced as a
        ``StreamSpan`` (open -> first-event TTFT -> per-event marks ->
        close/error/abandon) and a ``traceparent`` header joins it to the
        server's access record for the generation."""
        hdrs = dict(headers or {})
        span = self._obs_begin_stream(self._FRONTEND, model_name)
        self._last_stream_span = span
        if span is not None:
            hdrs[TRACEPARENT_HEADER] = span.traceparent()
        request = Request(hdrs)
        self._call_plugin(request)
        url = f"{self._base}/{self._generate_path(model_name, model_version, stream=True)}"
        body = self._generate_payload(inputs, request_id, parameters)
        tel = self._telemetry
        try:
            try:
                # no total timeout: generation streams for as long as it
                # streams
                async with self._session.post(
                    url, data=body, headers=request.headers,
                    params=query_params,
                    timeout=aiohttp.ClientTimeout(total=None),
                ) as resp:
                    if resp.status != 200:
                        raise_if_error(resp.status, await resp.read())
                        # 2xx-not-200/3xx from an intermediary:
                        # raise_if_error is a no-op below 400, and falling
                        # through would yield an empty stream with no error
                        raise InferenceServerException(
                            f"unexpected generate_stream status {resp.status}")
                    # chunked reads through the shared SSEDecoder (same
                    # framing as the sync client): no 64 KiB StreamReader
                    # line ceiling for large streamed tensors, CRLF event
                    # framing streams instead of buffering to EOF,
                    # multi-line data: fields join
                    decoder = SSEDecoder()
                    # mark at parse time (arrival), before the consumer
                    # runs; bound once so the disabled path is a None check
                    mark = span.mark if span is not None else None
                    # opt-in stream-index integrity (strict monotonicity
                    # within THIS wire stream); None when the policy is off
                    checker = self._integrity_stream_checker(model_name)
                    async for chunk in resp.content.iter_chunked(8192):
                        for payload in decoder.feed(chunk):
                            event = parse_sse_event(payload)
                            if checker is not None:
                                checker.observe(event)
                            if mark is not None:
                                mark()
                            yield event
                    for payload in decoder.flush():
                        event = parse_sse_event(payload)
                        if checker is not None:
                            checker.observe(event)
                        if mark is not None:
                            mark()
                        yield event
            except aiohttp.ClientError as e:
                raise InferenceServerException(f"connection error: {e}") from e
        except GeneratorExit:
            if span is not None:
                tel.finish_stream(span, abandoned=True)
            raise
        except BaseException as e:
            if span is not None:
                tel.finish_stream(span, error=e)
            raise
        if span is not None:
            tel.finish_stream(span)

    def last_stream_span(self):
        """The most recent ``generate_stream``'s StreamSpan (None without
        telemetry) — harnesses read TTFT/ITL from it instead of
        re-measuring with their own stopwatch."""
        return getattr(self, "_last_stream_span", None)
