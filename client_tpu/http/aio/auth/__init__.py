"""Auth plugins for the asyncio client (reference: */aio/auth subpackage).

Plugins are transport-agnostic here — BasicAuth from the shared base works
on sync and aio clients alike; this module mirrors the reference import path.
"""

from ...._base import BasicAuth, InferenceServerClientPlugin

__all__ = ["BasicAuth", "InferenceServerClientPlugin"]
