"""Synchronous KServe v2 HTTP/REST client.

Full-surface parity with the reference's
``tritonclient.http.InferenceServerClient`` (http/_client.py:102-1658):
infer / async_infer, health, metadata, config, repository control,
statistics, trace & log settings, and shared-memory registration — plus the
TPU extension endpoints (``v2/tpusharedmemory/...``) that pair with
``client_tpu.utils.tpu_shared_memory``.

Transport: urllib3 connection pool (the reference uses geventhttpclient;
urllib3 gives the same persistent-connection pooling without a greenlet
runtime). ``async_infer`` runs on a thread pool and returns an
``InferAsyncRequest`` future wrapper.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence
from urllib.parse import quote, urlencode

import urllib3

from .._base import (
    SHM_FAMILY_OF,
    InferenceServerClientBase,
    InferStat,
    Request,
    RequestTimers,
)
from .._tensor import InferInput, InferRequestedOutput
from ..observe import TRACEPARENT_HEADER
from ..resilience import (
    FATAL,
    RETRYABLE_HTTP_STATUSES,
    AttemptBudget,
    RetryableStatusError,
    classify_fault,
    connect_only_policy,
)
from ..integrity import IntegrityError
from ..utils import InferenceServerException
from ._infer_result import InferResult
from ._utils import (
    SSEDecoder,
    build_infer_body,
    compress_body,
    decompress_body,
    parse_sse_event,
    raise_if_error,
)


class _Response:
    """A fully-read HTTP response (body already Content-Encoding-decoded)."""

    __slots__ = ("status", "headers", "data")

    def __init__(self, status, headers, data):
        self.status = status
        self.headers = headers
        self.data = data


class InferAsyncRequest:
    """Handle for an in-flight async_infer; ``get_result`` blocks for the result."""

    def __init__(self, future: Future, verbose: bool = False):
        self._future = future
        self._verbose = verbose

    def get_result(self, block: bool = True, timeout: Optional[float] = None) -> InferResult:
        if not block and not self._future.done():
            raise InferenceServerException("inference request not yet completed")
        try:
            return self._future.result(timeout=timeout)
        except InferenceServerException:
            raise
        except Exception as e:  # transport-level failure
            raise InferenceServerException(f"inference request failed: {e}") from e

    def cancel(self) -> bool:
        return self._future.cancel()


class InferenceServerClient(InferenceServerClientBase):
    """Client for the KServe v2 HTTP/REST protocol.

    Note: like the reference client, one instance should be driven from one
    thread at a time for sync calls; ``async_infer`` is internally pooled.
    """

    _FRONTEND = "http"

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        concurrency: int = 1,
        connection_timeout: float = 60.0,
        network_timeout: float = 60.0,
        max_greenlets: Optional[int] = None,  # accepted for API parity; unused
        ssl: bool = False,
        ssl_options: Optional[Dict[str, Any]] = None,
        ssl_context_factory: Any = None,
        insecure: bool = False,
        max_retries: int = 0,
    ):
        """``max_retries``: re-attempts on *connect* failures (connection
        refused / DNS), where the request provably never reached the server —
        the safe subset of the reference Java client's retry loop
        (InferenceServerClient.java:293-317). In-flight failures are never
        retried (inference is not idempotent for sequences)."""
        super().__init__()
        if "://" in url:
            raise InferenceServerException(
                f"unexpected scheme in url '{url}' (pass host:port; use ssl=True for https)"
            )
        self._url = url
        self._verbose = verbose
        self._concurrency = max(1, concurrency)
        self._timeout = urllib3.Timeout(connect=connection_timeout, read=network_timeout)
        host, _, port = url.partition(":")
        port_num = int(port) if port else (443 if ssl else 80)
        pool_kwargs: Dict[str, Any] = dict(
            host=host,
            port=port_num,
            maxsize=self._concurrency,
            timeout=self._timeout,
            retries=False,
        )
        if ssl:
            opts = dict(ssl_options or {})
            if insecure:
                pool_kwargs["cert_reqs"] = "CERT_NONE"
            if "keyfile" in opts:
                pool_kwargs["key_file"] = opts["keyfile"]
            if "certfile" in opts:
                pool_kwargs["cert_file"] = opts["certfile"]
            if "ca_certs" in opts:
                pool_kwargs["ca_certs"] = opts["ca_certs"]
            if ssl_context_factory is not None:
                pool_kwargs["ssl_context"] = ssl_context_factory()
            self._pool = urllib3.HTTPSConnectionPool(**pool_kwargs)
        else:
            self._pool = urllib3.HTTPConnectionPool(**pool_kwargs)
        self._executor: Optional[ThreadPoolExecutor] = None
        self._executor_lock = threading.Lock()
        self._infer_stat = InferStat()
        self._max_retries = max(0, max_retries)
        # legacy knob as a policy: connect-only retries, no breaker; a
        # configure_resilience() policy takes precedence when installed
        self._legacy_policy = connect_only_policy(self._max_retries)

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
        self._pool.close()

    def __enter__(self) -> "InferenceServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- stats -------------------------------------------------------------
    def client_infer_stat(self) -> Dict[str, int]:
        """Cumulative client-side inference statistics (see InferStat)."""
        return self._infer_stat.as_dict()

    # -- transport ---------------------------------------------------------
    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        timeout: Optional[float] = None,
        timers: Optional[RequestTimers] = None,
        idempotent: bool = True,
        resilience=None,
        span=None,
    ):
        """Issue one HTTP request; returns the response with the body read.

        Content-Encoding is decoded by urllib3 (``decode_content``), so
        ``resp.data`` is always the plain payload. When ``timers`` is given,
        SEND_END is captured once response headers arrive and RECV_START/END
        bracket the body read.

        The request runs under the client's resilience policy (or the
        per-request ``resilience`` override): connect failures are always
        re-attemptable; in-flight resets and shed-load statuses
        (408/429/502/503/504) only when ``idempotent`` — sequence infers
        must never be silently re-sent after the bytes may have landed.
        """
        uri = "/" + path
        if query_params:
            uri += "?" + urlencode(query_params)
        if resilience is False:  # explicit bypass (health probes): raw, even past the legacy knob
            policy = None
        else:
            policy = self._resilience_for(resilience) or self._legacy_policy
        kwargs: Dict[str, Any] = dict(preload_content=False)
        if body is not None:
            kwargs["body"] = body
        budget = AttemptBudget(policy, timeout)
        retry_statuses = policy is not None and policy.retry_http_statuses

        def attempt() -> _Response:
            # plugin runs per attempt: a token-refreshing plugin must be
            # able to stamp a FRESH credential on every retry
            request = Request(dict(headers or {}))
            self._call_plugin(request)
            kwargs["headers"] = request.headers
            if self._verbose:
                print(f"{method} {uri}, headers {request.headers}")
            remaining = budget.attempt_timeout_s(status="499")
            if remaining is not None:
                kwargs["timeout"] = urllib3.Timeout(
                    connect=remaining, read=remaining)
            resp = None
            t_send = time.perf_counter_ns() if span is not None else 0
            try:
                try:
                    resp = self._pool.request(method, uri, **kwargs)
                except urllib3.exceptions.NewConnectionError as e:
                    # must precede TimeoutError: NewConnectionError subclasses
                    # ConnectTimeoutError in urllib3, but "refused" isn't
                    # "timed out". classify_fault sees the cause type and
                    # files this under the connect domain (always safe).
                    raise InferenceServerException(
                        f"connection error: {e}") from e
                if span is not None:
                    # per ATTEMPT (a retried request must not fold its
                    # predecessors' failures + backoff into ttfb)
                    t_hdrs = time.perf_counter_ns()
                    span.phase("ttfb", t_send, t_hdrs)
                if timers is not None:
                    timers.capture(RequestTimers.SEND_END)
                    timers.capture(RequestTimers.RECV_START)
                data = resp.read(decode_content=True)
                if span is not None:
                    span.phase("recv", t_hdrs, time.perf_counter_ns())
                if timers is not None:
                    timers.capture(RequestTimers.RECV_END)
            except urllib3.exceptions.TimeoutError as e:
                raise InferenceServerException(
                    "Deadline Exceeded", status="499") from e
            except urllib3.exceptions.HTTPError as e:
                raise InferenceServerException(f"connection error: {e}") from e
            finally:
                if resp is not None:
                    resp.release_conn()
            if self._verbose:
                print(f"-> {resp.status}, headers {dict(resp.headers)}")
            out = _Response(resp.status, resp.headers, data)
            if retry_statuses and str(resp.status) in RETRYABLE_HTTP_STATUSES:
                raise RetryableStatusError(resp.status, out)
            return out

        run_attempt = attempt
        if span is not None:
            def run_attempt():
                # retry-attempt sub-span: each resilient attempt shows up
                # as its own interval in the trace timeline
                t_a = time.perf_counter_ns()
                try:
                    return attempt()
                finally:
                    span.phase("attempt", t_a, time.perf_counter_ns())

        if policy is None:
            return run_attempt()
        on_retry = None
        if self._verbose or span is not None:
            def on_retry(n, exc, delay):
                if span is not None:
                    span.event("retry", attempt=n,
                               backoff_s=round(delay, 6),
                               error=type(exc).__name__)
                if self._verbose:
                    print(f"retrying after attempt {n + 1} failed ({exc}); "
                          f"backoff {delay:.3f}s")
        try:
            return policy.execute(
                run_attempt, idempotent=idempotent, timeout_s=timeout,
                on_retry=on_retry,
            )
        except RetryableStatusError as e:
            # attempts exhausted on a shed-load status: hand the original
            # response back so callers keep the plain raise_if_error path
            return e.response

    def _get(self, path, headers=None, query_params=None):
        return self._request("GET", path, headers=headers, query_params=query_params)

    def _post(self, path, body=b"", headers=None, query_params=None, timeout=None,
              timers=None, idempotent=True, resilience=None, span=None):
        return self._request(
            "POST", path, body=body, headers=headers, query_params=query_params,
            timeout=timeout, timers=timers, idempotent=idempotent,
            resilience=resilience, span=span,
        )

    @staticmethod
    def _json_of(resp) -> Dict[str, Any]:
        raise_if_error(resp.status, resp.data)
        return json.loads(resp.data) if resp.data else {}

    # -- health / metadata -------------------------------------------------
    def _health(self, path, headers, query_params, probe: bool,
                client_timeout: Optional[float]) -> bool:
        """Shared live/ready GET. Default semantics match the reference:
        transport failures (connection refused, resets, timeouts) RAISE —
        callers distinguish "server said not ready" from "could not ask".
        ``probe=True`` is the health-poller mode: connect/transient/timeout
        -class failures return False instead (a dead endpoint is not ready),
        and the request bypasses any configured resilience policy so the
        probe observes the endpoint, never a breaker's fast-fail. FATAL
        (application/protocol) errors still raise."""
        try:
            resp = self._request(
                "GET", path, headers=headers, query_params=query_params,
                timeout=client_timeout,
                resilience=False if probe else None,
            )
        except InferenceServerException as e:
            if probe and classify_fault(e) != FATAL:
                return False
            raise
        return resp.status == 200

    def is_server_live(self, headers=None, query_params=None,
                       probe: bool = False,
                       client_timeout: Optional[float] = None) -> bool:
        return self._health(
            "v2/health/live", headers, query_params, probe, client_timeout)

    def is_server_ready(self, headers=None, query_params=None,
                        probe: bool = False,
                        client_timeout: Optional[float] = None) -> bool:
        return self._health(
            "v2/health/ready", headers, query_params, probe, client_timeout)

    def is_model_ready(self, model_name, model_version="", headers=None, query_params=None) -> bool:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return self._get(path + "/ready", headers, query_params).status == 200

    def get_server_metadata(self, headers=None, query_params=None) -> Dict[str, Any]:
        return self._json_of(self._get("v2", headers, query_params))

    def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> Dict[str, Any]:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        metadata = self._json_of(self._get(path, headers, query_params))
        # captured into the integrity contract cache: later responses
        # are validated against this fetched truth (never vice versa)
        self._integrity_note_metadata(model_name, metadata)
        return metadata

    def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ) -> Dict[str, Any]:
        path = f"v2/models/{quote(model_name)}"
        if model_version:
            path += f"/versions/{model_version}"
        return self._json_of(self._get(path + "/config", headers, query_params))

    # -- repository control ------------------------------------------------
    def get_model_repository_index(self, headers=None, query_params=None) -> List[Dict[str, Any]]:
        resp = self._post("v2/repository/index", b"", headers, query_params)
        raise_if_error(resp.status, resp.data)
        return json.loads(resp.data) if resp.data else []

    def load_model(
        self, model_name, headers=None, query_params=None, config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None,
    ) -> None:
        import base64

        body: Dict[str, Any] = {}
        params: Dict[str, Any] = {}
        if config is not None:
            params["config"] = config
        if files:
            for path, content in files.items():
                params[path] = base64.b64encode(content).decode("ascii")
        if params:
            body["parameters"] = params
        resp = self._post(
            f"v2/repository/models/{quote(model_name)}/load",
            json.dumps(body).encode("utf-8"),
            headers,
            query_params,
        )
        raise_if_error(resp.status, resp.data)

    def unload_model(
        self, model_name, headers=None, query_params=None, unload_dependents: bool = False
    ) -> None:
        body = {"parameters": {"unload_dependents": unload_dependents}}
        resp = self._post(
            f"v2/repository/models/{quote(model_name)}/unload",
            json.dumps(body).encode("utf-8"),
            headers,
            query_params,
        )
        raise_if_error(resp.status, resp.data)

    # -- statistics / trace / log -------------------------------------------
    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, query_params=None
    ) -> Dict[str, Any]:
        if model_name:
            path = f"v2/models/{quote(model_name)}"
            if model_version:
                path += f"/versions/{model_version}"
            path += "/stats"
        else:
            path = "v2/models/stats"
        return self._json_of(self._get(path, headers, query_params))

    def update_trace_settings(
        self, model_name=None, settings: Optional[Dict[str, Any]] = None,
        headers=None, query_params=None,
    ) -> Dict[str, Any]:
        path = (
            f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        )
        resp = self._post(
            path, json.dumps(settings or {}).encode("utf-8"), headers, query_params
        )
        return self._json_of(resp)

    def get_trace_settings(self, model_name=None, headers=None, query_params=None) -> Dict[str, Any]:
        path = (
            f"v2/models/{quote(model_name)}/trace/setting" if model_name else "v2/trace/setting"
        )
        return self._json_of(self._get(path, headers, query_params))

    def update_log_settings(
        self, settings: Dict[str, Any], headers=None, query_params=None
    ) -> Dict[str, Any]:
        resp = self._post("v2/logging", json.dumps(settings).encode("utf-8"), headers, query_params)
        return self._json_of(resp)

    def get_log_settings(self, headers=None, query_params=None) -> Dict[str, Any]:
        return self._json_of(self._get("v2/logging", headers, query_params))

    # -- shared memory -----------------------------------------------------
    def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ) -> List[Dict[str, Any]]:
        return self._shm_status("systemsharedmemory", region_name, headers, query_params)

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ) -> None:
        def call():
            body = {"key": key, "offset": offset, "byte_size": byte_size}
            resp = self._post(
                f"v2/systemsharedmemory/region/{quote(name)}/register",
                json.dumps(body).encode("utf-8"),
                headers,
                query_params,
            )
            raise_if_error(resp.status, resp.data)

        self._shm_call("system", "register", call)

    def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ) -> None:
        self._shm_unregister("systemsharedmemory", name, headers, query_params)

    def _shm_register(self, family, name, raw_handle, device_id, byte_size, headers, query_params):
        def call():
            body = {
                "raw_handle": {"b64": raw_handle},
                "device_id": device_id,
                "byte_size": byte_size,
            }
            resp = self._post(
                f"v2/{family}/region/{quote(name)}/register",
                json.dumps(body).encode("utf-8"),
                headers,
                query_params,
            )
            raise_if_error(resp.status, resp.data)

        self._shm_call(SHM_FAMILY_OF[family], "register", call)

    def _shm_status(self, family, region_name, headers, query_params):
        path = f"v2/{family}"
        if region_name:
            path += f"/region/{quote(region_name)}"
        path += "/status"
        resp = self._get(path, headers, query_params)
        raise_if_error(resp.status, resp.data)
        return json.loads(resp.data) if resp.data else []

    def _shm_unregister(self, family, name, headers, query_params):
        def call():
            path = f"v2/{family}"
            if name:
                path += f"/region/{quote(name)}"
            path += "/unregister"
            resp = self._post(path, b"", headers, query_params)
            raise_if_error(resp.status, resp.data)

        self._shm_call(SHM_FAMILY_OF[family], "unregister", call,
                       region_name=name)

    def get_cuda_shared_memory_status(self, region_name="", headers=None, query_params=None):
        return self._shm_status("cudasharedmemory", region_name, headers, query_params)

    def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ) -> None:
        self._shm_register(
            "cudasharedmemory", name, raw_handle, device_id, byte_size, headers, query_params
        )

    def unregister_cuda_shared_memory(self, name="", headers=None, query_params=None) -> None:
        self._shm_unregister("cudasharedmemory", name, headers, query_params)

    def get_tpu_shared_memory_status(self, region_name="", headers=None, query_params=None):
        return self._shm_status("tpusharedmemory", region_name, headers, query_params)

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, query_params=None
    ) -> None:
        """Register a tpu_shared_memory region (see utils.tpu_shared_memory).

        ``raw_handle`` is the base64 descriptor from ``get_raw_handle``.
        """
        self._shm_register(
            "tpusharedmemory", name, raw_handle, device_id, byte_size, headers, query_params
        )

    def unregister_tpu_shared_memory(self, name="", headers=None, query_params=None) -> None:
        self._shm_unregister("tpusharedmemory", name, headers, query_params)

    # -- inference ---------------------------------------------------------
    @staticmethod
    def generate_request_body(
        inputs: Sequence[InferInput],
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        **kwargs,
    ):
        """Offline marshaling: returns (body, json_size)."""
        return build_infer_body(inputs, outputs, **kwargs)

    @staticmethod
    def parse_response_body(
        response_body: bytes, verbose: bool = False, header_length: Optional[int] = None,
        content_encoding: Optional[str] = None,
    ) -> InferResult:
        body = decompress_body(response_body, content_encoding)
        return InferResult.from_response_body(body, header_length)

    def _infer_uri(self, model_name: str, model_version: str) -> str:
        uri = f"v2/models/{quote(model_name)}"
        if model_version:
            uri += f"/versions/{model_version}"
        return uri + "/infer"

    def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        request_compression_algorithm: Optional[str] = None,
        response_compression_algorithm: Optional[str] = None,
        parameters: Optional[Dict[str, Any]] = None,
        resilience=None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        """Run a synchronous inference.

        ``resilience``: per-request ``ResiliencePolicy`` override. Sequence
        requests (``sequence_id != 0``) are non-idempotent: only
        never-sent connect failures are retried for them.

        ``tenant``: client-side QoS attribution (see
        ``client_tpu.tenancy``) — recorded on the request's span, NEVER
        sent on the wire; quota/fairness enforcement happens in the
        pool's admission gate, which consumes the kwarg before it
        reaches a frontend."""
        span = self._obs_begin(self._FRONTEND, model_name)
        if span is not None and tenant is not None:
            span.event("tenant", tenant=tenant)
        timers = RequestTimers()
        timers.capture(RequestTimers.REQUEST_START)
        actx = None
        try:
            # arena data plane: promote staged binary inputs into leased
            # slabs and ensure (cached) region registrations BEFORE the
            # body is built, so the request rides shm params
            actx = self._arena_bind(inputs, outputs)
            body, json_size = build_infer_body(
                inputs,
                outputs,
                request_id,
                sequence_id,
                sequence_start,
                sequence_end,
                priority,
                timeout,
                parameters,
            )
            hdrs = self._orca_opt_in(dict(headers or {}))
            body, encoding = compress_body(body, request_compression_algorithm)
            if encoding:
                hdrs["Content-Encoding"] = encoding
            if response_compression_algorithm in ("gzip", "deflate"):
                hdrs["Accept-Encoding"] = response_compression_algorithm
            if json_size is not None:
                hdrs["Inference-Header-Content-Length"] = str(json_size)
                hdrs["Content-Type"] = "application/octet-stream"
            else:
                hdrs["Content-Type"] = "application/json"
            if span is not None:
                hdrs[TRACEPARENT_HEADER] = span.traceparent()
                span.phase("serialize", span.start_ns,
                           time.perf_counter_ns())

            timers.capture(RequestTimers.SEND_START)
            resp = self._post(
                self._infer_uri(model_name, model_version),
                body,
                hdrs,
                query_params,
                timeout=client_timeout,
                timers=timers,
                idempotent=sequence_id == 0,
                resilience=resilience,
                span=span,
            )
            # urllib3 already decoded any Content-Encoding; resp.data is plain.
            raise_if_error(resp.status, resp.data)
            t_deser = time.perf_counter_ns() if span is not None else 0
            header_length = resp.headers.get("Inference-Header-Content-Length")
            try:
                result = InferResult.from_response_body(
                    resp.data,
                    int(header_length) if header_length is not None else None,
                )
            except IntegrityError as e:
                # undecodable body (torn JSON, overrun binary sizes):
                # attribute to this endpoint and account like any other
                # integrity violation, then let it classify as INVALID
                self._integrity_parse_note(e)
                raise
            result._response_headers = dict(resp.headers)  # e.g. endpoint-load-metrics
            if actx is not None:
                actx.finish(result)
            # contract validation: the result never reaches the caller
            # (nor the ORCA/verbose paths below) un-checked
            self._integrity_check(result, inputs, outputs, request_id,
                                  model_name)
        except BaseException as e:
            if span is not None:
                self._telemetry.finish(span, error=e)
            raise
        finally:
            # response fully received: promoted input leases release and
            # the inputs' wire staging is restored for reuse
            if actx is not None:
                actx.settle()
        timers.capture(RequestTimers.REQUEST_END)
        self._infer_stat.update(timers)
        if span is not None:
            span.phase("deserialize", t_deser, time.perf_counter_ns())
            self._telemetry.finish(span)
        # after the phase capture: ORCA bookkeeping (header parse + gauge
        # writes) must not masquerade as deserialize milliseconds
        self._orca_ingest(result)
        if self._verbose:
            print(result.get_response())
        return result

    def async_infer(self, model_name: str, inputs: Sequence[InferInput], **kwargs) -> InferAsyncRequest:
        """Submit an inference on the client's thread pool; returns a handle."""
        with self._executor_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._concurrency, thread_name_prefix="client_tpu_http"
                )
        future = self._executor.submit(self.infer, model_name, inputs, **kwargs)
        return InferAsyncRequest(future, self._verbose)

    # -- generate extension (LLM JSON API) ----------------------------------
    # Server counterpart: the generate/generate_stream routes on both HTTP
    # frontends (reference protocol: tritonserver extension_generate — flat
    # JSON keys map to input tensors; streaming responses arrive as SSE).
    @staticmethod
    def _generate_path(model_name: str, model_version: str, stream: bool) -> str:
        tail = "generate_stream" if stream else "generate"
        if model_version:
            return f"v2/models/{quote(model_name)}/versions/{model_version}/{tail}"
        return f"v2/models/{quote(model_name)}/{tail}"

    @staticmethod
    def _generate_payload(inputs, request_id, parameters) -> bytes:
        payload = dict(inputs)
        if request_id:
            payload["id"] = request_id
        if parameters:
            payload["parameters"] = parameters
        return json.dumps(payload).encode("utf-8")

    def generate(
        self,
        model_name: str,
        inputs: Dict[str, Any],
        model_version: str = "",
        request_id: str = "",
        parameters: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """One-shot generate: flat JSON in, flat JSON out (the model must
        produce exactly one response; decoupled many-response models need
        :meth:`generate_stream`)."""
        resp = self._request(
            "POST",
            self._generate_path(model_name, model_version, stream=False),
            self._generate_payload(inputs, request_id, parameters),
            headers, query_params,
        )
        raise_if_error(resp.status, resp.data)
        return json.loads(resp.data)

    def generate_stream(
        self,
        model_name: str,
        inputs: Dict[str, Any],
        model_version: str = "",
        request_id: str = "",
        parameters: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
    ):
        """Iterator over generate-extension SSE events, one dict per
        streamed response. Abandoning the iterator mid-stream closes the
        connection, which the server accounts as a client cancel (the
        cancel stats bucket), not a success. In-band error events raise.

        With telemetry configured the stream is traced as a
        ``StreamSpan`` (open -> first-event TTFT -> per-event marks ->
        close/error/abandon) and a ``traceparent`` header joins it to the
        server's access record for the generation. ``tenant`` is
        client-side QoS attribution only (see ``client_tpu.tenancy``) —
        marked on the stream span, never sent on the wire."""
        hdrs = dict(headers or {})
        span = self._obs_begin_stream(self._FRONTEND, model_name)
        self._last_stream_span = span
        if span is not None and tenant is not None:
            span.event("tenant", tenant=tenant)
        if span is not None:
            hdrs[TRACEPARENT_HEADER] = span.traceparent()
        request = Request(hdrs)
        self._call_plugin(request)
        uri = "/" + self._generate_path(model_name, model_version, stream=True)
        if query_params:
            uri += "?" + urlencode(query_params)
        tel = self._telemetry
        try:
            try:
                # no read deadline: generation streams for as long as it
                # streams (matches the aio twin's ClientTimeout(total=None));
                # the pool's connect timeout still applies
                resp = self._pool.request(
                    "POST", uri,
                    body=self._generate_payload(
                        inputs, request_id, parameters),
                    headers=request.headers, preload_content=False,
                    timeout=urllib3.Timeout(
                        connect=self._timeout.connect_timeout, read=None),
                )
            except urllib3.exceptions.HTTPError as e:
                raise InferenceServerException(
                    f"connection error: {e}") from e
            exhausted = False
            try:
                if resp.status != 200:
                    try:
                        data = resp.read(decode_content=True)
                    except urllib3.exceptions.HTTPError as e:
                        raise InferenceServerException(
                            f"connection error: {e}") from e
                    raise_if_error(resp.status, data)
                    raise InferenceServerException(
                        f"unexpected generate_stream status {resp.status}")
                # SSEDecoder: CRLF-framed servers stream event-by-event (a
                # bare \n\n split would buffer them to EOF), multi-line
                # data: fields join per the SSE spec, and a final event
                # whose terminating blank line never arrived is flushed,
                # not dropped
                decoder = SSEDecoder()
                # mark at parse time (arrival), before the consumer runs;
                # bound once so the disabled path is a single None check
                mark = span.mark if span is not None else None
                # opt-in stream-index integrity (strict monotonicity
                # within THIS wire stream); None when the policy is off
                checker = self._integrity_stream_checker(model_name)
                try:
                    for chunk in resp.stream(8192, decode_content=True):
                        for payload in decoder.feed(chunk):
                            event = parse_sse_event(payload)
                            if checker is not None:
                                checker.observe(event)
                            if mark is not None:
                                mark()
                            yield event
                    for payload in decoder.flush():
                        event = parse_sse_event(payload)
                        if checker is not None:
                            checker.observe(event)
                        if mark is not None:
                            mark()
                        yield event
                except urllib3.exceptions.HTTPError as e:
                    # server died mid-stream etc. — keep the client's typed
                    # exception contract (the aio twin wraps ClientError)
                    raise InferenceServerException(
                        f"connection error: {e}") from e
                exhausted = True
            finally:
                if exhausted:
                    # fully-drained chunked body: the connection is
                    # reusable — back to the pool, so per-session TTFT
                    # doesn't pay a fresh TCP handshake (genai_perf
                    # generate-mode bias)
                    resp.release_conn()
                else:
                    # close (not release): an abandoned stream must tear
                    # the connection down so the server sees the disconnect
                    resp.close()
        except GeneratorExit:
            if span is not None:
                tel.finish_stream(span, abandoned=True)
            raise
        except BaseException as e:
            if span is not None:
                tel.finish_stream(span, error=e)
            raise
        if span is not None:
            tel.finish_stream(span)

    def last_stream_span(self):
        """The most recent ``generate_stream``'s StreamSpan (None without
        telemetry) — harnesses read TTFT/ITL from it instead of
        re-measuring with their own stopwatch."""
        return getattr(self, "_last_stream_span", None)
