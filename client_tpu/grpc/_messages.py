"""Message specs for ``inference.GRPCInferenceService`` (KServe v2 GRPC).

Field numbers follow the public KServe/Triton protocol definition (reference:
src/rust/triton-client/proto/grpc_service.proto — service :40, ModelInfer
messages :575-820, shm messages :1403-1460, trace/log :1660-1737;
model_config.proto for the ModelConfig subset) so the wire format
interoperates with any v2 server. The codec is ``_wire.py``.
"""

from __future__ import annotations

from ._wire import MessageSpec, map_field, message, scalar

# ---------------------------------------------------------------------------
# shared sub-messages
# ---------------------------------------------------------------------------

INFER_PARAMETER = MessageSpec(
    "InferParameter",
    [
        scalar("bool_param", 1, "bool", oneof="parameter_choice"),
        scalar("int64_param", 2, "int64", oneof="parameter_choice"),
        scalar("string_param", 3, "string", oneof="parameter_choice"),
        scalar("double_param", 4, "double", oneof="parameter_choice"),
        scalar("uint64_param", 5, "uint64", oneof="parameter_choice"),
    ],
)

INFER_TENSOR_CONTENTS = MessageSpec(
    "InferTensorContents",
    [
        scalar("bool_contents", 1, "bool", repeated=True),
        scalar("int_contents", 2, "int32", repeated=True),
        scalar("int64_contents", 3, "int64", repeated=True),
        scalar("uint_contents", 4, "uint32", repeated=True),
        scalar("uint64_contents", 5, "uint64", repeated=True),
        scalar("fp32_contents", 6, "float", repeated=True),
        scalar("fp64_contents", 7, "double", repeated=True),
        scalar("bytes_contents", 8, "bytes", repeated=True),
    ],
)

INFER_INPUT_TENSOR = MessageSpec(
    "ModelInferRequest.InferInputTensor",
    [
        scalar("name", 1, "string"),
        scalar("datatype", 2, "string"),
        scalar("shape", 3, "int64", repeated=True),
        map_field("parameters", 4, "string", INFER_PARAMETER),
        message("contents", 5, INFER_TENSOR_CONTENTS),
    ],
)

INFER_REQUESTED_OUTPUT_TENSOR = MessageSpec(
    "ModelInferRequest.InferRequestedOutputTensor",
    [
        scalar("name", 1, "string"),
        map_field("parameters", 2, "string", INFER_PARAMETER),
    ],
)

MODEL_INFER_REQUEST = MessageSpec(
    "ModelInferRequest",
    [
        scalar("model_name", 1, "string"),
        scalar("model_version", 2, "string"),
        scalar("id", 3, "string"),
        map_field("parameters", 4, "string", INFER_PARAMETER),
        message("inputs", 5, INFER_INPUT_TENSOR, repeated=True),
        message("outputs", 6, INFER_REQUESTED_OUTPUT_TENSOR, repeated=True),
        scalar("raw_input_contents", 7, "bytes", repeated=True),
    ],
)

INFER_OUTPUT_TENSOR = MessageSpec(
    "ModelInferResponse.InferOutputTensor",
    [
        scalar("name", 1, "string"),
        scalar("datatype", 2, "string"),
        scalar("shape", 3, "int64", repeated=True),
        map_field("parameters", 4, "string", INFER_PARAMETER),
        message("contents", 5, INFER_TENSOR_CONTENTS),
    ],
)

MODEL_INFER_RESPONSE = MessageSpec(
    "ModelInferResponse",
    [
        scalar("model_name", 1, "string"),
        scalar("model_version", 2, "string"),
        scalar("id", 3, "string"),
        map_field("parameters", 4, "string", INFER_PARAMETER),
        message("outputs", 5, INFER_OUTPUT_TENSOR, repeated=True),
        scalar("raw_output_contents", 6, "bytes", repeated=True),
    ],
)

MODEL_STREAM_INFER_RESPONSE = MessageSpec(
    "ModelStreamInferResponse",
    [
        scalar("error_message", 1, "string"),
        message("infer_response", 2, MODEL_INFER_RESPONSE),
    ],
)

# ---------------------------------------------------------------------------
# health / metadata
# ---------------------------------------------------------------------------

EMPTY = MessageSpec("Empty", [])
SERVER_LIVE_RESPONSE = MessageSpec("ServerLiveResponse", [scalar("live", 1, "bool")])
SERVER_READY_RESPONSE = MessageSpec("ServerReadyResponse", [scalar("ready", 1, "bool")])
MODEL_READY_REQUEST = MessageSpec(
    "ModelReadyRequest", [scalar("name", 1, "string"), scalar("version", 2, "string")]
)
MODEL_READY_RESPONSE = MessageSpec("ModelReadyResponse", [scalar("ready", 1, "bool")])

SERVER_METADATA_RESPONSE = MessageSpec(
    "ServerMetadataResponse",
    [
        scalar("name", 1, "string"),
        scalar("version", 2, "string"),
        scalar("extensions", 3, "string", repeated=True),
    ],
)

MODEL_METADATA_REQUEST = MessageSpec(
    "ModelMetadataRequest", [scalar("name", 1, "string"), scalar("version", 2, "string")]
)

TENSOR_METADATA = MessageSpec(
    "TensorMetadata",
    [
        scalar("name", 1, "string"),
        scalar("datatype", 2, "string"),
        scalar("shape", 3, "int64", repeated=True),
    ],
)

MODEL_METADATA_RESPONSE = MessageSpec(
    "ModelMetadataResponse",
    [
        scalar("name", 1, "string"),
        scalar("versions", 2, "string", repeated=True),
        scalar("platform", 3, "string"),
        message("inputs", 4, TENSOR_METADATA, repeated=True),
        message("outputs", 5, TENSOR_METADATA, repeated=True),
    ],
)

# ---------------------------------------------------------------------------
# model config (commonly-consumed subset; unknown fields are skipped)
# ---------------------------------------------------------------------------

# DataType enum (model_config.proto): TYPE_INVALID=0, TYPE_BOOL=1, TYPE_UINT8=2,
# TYPE_UINT16=3, TYPE_UINT32=4, TYPE_UINT64=5, TYPE_INT8=6, TYPE_INT16=7,
# TYPE_INT32=8, TYPE_INT64=9, TYPE_FP16=10, TYPE_FP32=11, TYPE_FP64=12,
# TYPE_STRING=13, TYPE_BF16=14
CONFIG_DATATYPE_NAMES = [
    "TYPE_INVALID", "TYPE_BOOL", "TYPE_UINT8", "TYPE_UINT16", "TYPE_UINT32",
    "TYPE_UINT64", "TYPE_INT8", "TYPE_INT16", "TYPE_INT32", "TYPE_INT64",
    "TYPE_FP16", "TYPE_FP32", "TYPE_FP64", "TYPE_STRING", "TYPE_BF16",
]

MODEL_TENSOR_RESHAPE = MessageSpec(
    "ModelTensorReshape", [scalar("shape", 1, "int64", repeated=True)]
)

MODEL_INPUT = MessageSpec(
    "ModelInput",
    [
        scalar("name", 1, "string"),
        scalar("data_type", 2, "enum"),
        scalar("format", 3, "enum"),
        scalar("dims", 4, "int64", repeated=True),
        message("reshape", 5, MODEL_TENSOR_RESHAPE),
        scalar("is_shape_tensor", 6, "bool"),
        scalar("allow_ragged_batch", 7, "bool"),
        scalar("optional", 8, "bool"),
    ],
)

MODEL_OUTPUT = MessageSpec(
    "ModelOutput",
    [
        scalar("name", 1, "string"),
        scalar("data_type", 2, "enum"),
        scalar("dims", 3, "int64", repeated=True),
        scalar("label_filename", 4, "string"),
        message("reshape", 5, MODEL_TENSOR_RESHAPE),
        scalar("is_shape_tensor", 6, "bool"),
    ],
)

MODEL_TRANSACTION_POLICY = MessageSpec(
    "ModelTransactionPolicy", [scalar("decoupled", 1, "bool")]
)

MODEL_CONFIG = MessageSpec(
    "ModelConfig",
    [
        scalar("name", 1, "string"),
        scalar("platform", 2, "string"),
        scalar("max_batch_size", 4, "int32"),
        message("input", 5, MODEL_INPUT, repeated=True),
        message("output", 6, MODEL_OUTPUT, repeated=True),
        scalar("default_model_filename", 8, "string"),
        scalar("backend", 17, "string"),
        message("model_transaction_policy", 19, MODEL_TRANSACTION_POLICY),
        scalar("runtime", 25, "string"),
    ],
)

MODEL_CONFIG_REQUEST = MessageSpec(
    "ModelConfigRequest", [scalar("name", 1, "string"), scalar("version", 2, "string")]
)
MODEL_CONFIG_RESPONSE = MessageSpec(
    "ModelConfigResponse", [message("config", 1, MODEL_CONFIG)]
)

# ---------------------------------------------------------------------------
# statistics
# ---------------------------------------------------------------------------

STATISTIC_DURATION = MessageSpec(
    "StatisticDuration", [scalar("count", 1, "uint64"), scalar("ns", 2, "uint64")]
)

INFER_STATISTICS = MessageSpec(
    "InferStatistics",
    [
        message("success", 1, STATISTIC_DURATION),
        message("fail", 2, STATISTIC_DURATION),
        message("queue", 3, STATISTIC_DURATION),
        message("compute_input", 4, STATISTIC_DURATION),
        message("compute_infer", 5, STATISTIC_DURATION),
        message("compute_output", 6, STATISTIC_DURATION),
        message("cache_hit", 7, STATISTIC_DURATION),
        message("cache_miss", 8, STATISTIC_DURATION),
        # extension past the reference protocol: client-abandoned requests
        # (neither success nor fail; see server/core.py record_cancel)
        message("cancel", 9, STATISTIC_DURATION),
    ],
)

INFER_BATCH_STATISTICS = MessageSpec(
    "InferBatchStatistics",
    [
        scalar("batch_size", 1, "uint64"),
        message("compute_input", 2, STATISTIC_DURATION),
        message("compute_infer", 3, STATISTIC_DURATION),
        message("compute_output", 4, STATISTIC_DURATION),
    ],
)

MODEL_STATISTICS = MessageSpec(
    "ModelStatistics",
    [
        scalar("name", 1, "string"),
        scalar("version", 2, "string"),
        scalar("last_inference", 3, "uint64"),
        scalar("inference_count", 4, "uint64"),
        scalar("execution_count", 5, "uint64"),
        message("inference_stats", 6, INFER_STATISTICS),
        message("batch_stats", 7, INFER_BATCH_STATISTICS, repeated=True),
    ],
)

MODEL_STATISTICS_REQUEST = MessageSpec(
    "ModelStatisticsRequest", [scalar("name", 1, "string"), scalar("version", 2, "string")]
)
MODEL_STATISTICS_RESPONSE = MessageSpec(
    "ModelStatisticsResponse", [message("model_stats", 1, MODEL_STATISTICS, repeated=True)]
)

# ---------------------------------------------------------------------------
# repository control
# ---------------------------------------------------------------------------

MODEL_REPOSITORY_PARAMETER = MessageSpec(
    "ModelRepositoryParameter",
    [
        scalar("bool_param", 1, "bool", oneof="parameter_choice"),
        scalar("int64_param", 2, "int64", oneof="parameter_choice"),
        scalar("string_param", 3, "string", oneof="parameter_choice"),
        scalar("bytes_param", 4, "bytes", oneof="parameter_choice"),
    ],
)

REPOSITORY_INDEX_REQUEST = MessageSpec(
    "RepositoryIndexRequest",
    [scalar("repository_name", 1, "string"), scalar("ready", 2, "bool")],
)

MODEL_INDEX = MessageSpec(
    "RepositoryIndexResponse.ModelIndex",
    [
        scalar("name", 1, "string"),
        scalar("version", 2, "string"),
        scalar("state", 3, "string"),
        scalar("reason", 4, "string"),
    ],
)

REPOSITORY_INDEX_RESPONSE = MessageSpec(
    "RepositoryIndexResponse", [message("models", 1, MODEL_INDEX, repeated=True)]
)

REPOSITORY_MODEL_LOAD_REQUEST = MessageSpec(
    "RepositoryModelLoadRequest",
    [
        scalar("repository_name", 1, "string"),
        scalar("model_name", 2, "string"),
        map_field("parameters", 3, "string", MODEL_REPOSITORY_PARAMETER),
    ],
)

REPOSITORY_MODEL_UNLOAD_REQUEST = MessageSpec(
    "RepositoryModelUnloadRequest",
    [
        scalar("repository_name", 1, "string"),
        scalar("model_name", 2, "string"),
        map_field("parameters", 3, "string", MODEL_REPOSITORY_PARAMETER),
    ],
)

# ---------------------------------------------------------------------------
# shared memory (system / cuda-format / tpu)
# ---------------------------------------------------------------------------

SYSTEM_SHM_REGION_STATUS = MessageSpec(
    "SystemSharedMemoryStatusResponse.RegionStatus",
    [
        scalar("name", 1, "string"),
        scalar("key", 2, "string"),
        scalar("offset", 3, "uint64"),
        scalar("byte_size", 4, "uint64"),
    ],
)

SYSTEM_SHM_STATUS_REQUEST = MessageSpec(
    "SystemSharedMemoryStatusRequest", [scalar("name", 1, "string")]
)
SYSTEM_SHM_STATUS_RESPONSE = MessageSpec(
    "SystemSharedMemoryStatusResponse",
    [map_field("regions", 1, "string", SYSTEM_SHM_REGION_STATUS)],
)
SYSTEM_SHM_REGISTER_REQUEST = MessageSpec(
    "SystemSharedMemoryRegisterRequest",
    [
        scalar("name", 1, "string"),
        scalar("key", 2, "string"),
        scalar("offset", 3, "uint64"),
        scalar("byte_size", 4, "uint64"),
    ],
)
SYSTEM_SHM_UNREGISTER_REQUEST = MessageSpec(
    "SystemSharedMemoryUnregisterRequest", [scalar("name", 1, "string")]
)

DEVICE_SHM_REGION_STATUS = MessageSpec(
    "CudaSharedMemoryStatusResponse.RegionStatus",
    [
        scalar("name", 1, "string"),
        scalar("device_id", 2, "uint64"),
        scalar("byte_size", 3, "uint64"),
    ],
)

DEVICE_SHM_STATUS_REQUEST = MessageSpec(
    "CudaSharedMemoryStatusRequest", [scalar("name", 1, "string")]
)
DEVICE_SHM_STATUS_RESPONSE = MessageSpec(
    "CudaSharedMemoryStatusResponse",
    [map_field("regions", 1, "string", DEVICE_SHM_REGION_STATUS)],
)
DEVICE_SHM_REGISTER_REQUEST = MessageSpec(
    "CudaSharedMemoryRegisterRequest",
    [
        scalar("name", 1, "string"),
        scalar("raw_handle", 2, "bytes"),
        scalar("device_id", 3, "int64"),
        scalar("byte_size", 4, "uint64"),
    ],
)
DEVICE_SHM_UNREGISTER_REQUEST = MessageSpec(
    "CudaSharedMemoryUnregisterRequest", [scalar("name", 1, "string")]
)

# ---------------------------------------------------------------------------
# trace / log settings
# ---------------------------------------------------------------------------

TRACE_SETTING_VALUE = MessageSpec(
    "TraceSettingRequest.SettingValue", [scalar("value", 1, "string", repeated=True)]
)

TRACE_SETTING_REQUEST = MessageSpec(
    "TraceSettingRequest",
    [
        map_field("settings", 1, "string", TRACE_SETTING_VALUE),
        scalar("model_name", 2, "string"),
    ],
)
TRACE_SETTING_RESPONSE = MessageSpec(
    "TraceSettingResponse", [map_field("settings", 1, "string", TRACE_SETTING_VALUE)]
)

LOG_SETTING_VALUE = MessageSpec(
    "LogSettingsRequest.SettingValue",
    [
        scalar("bool_param", 1, "bool", oneof="parameter_choice"),
        scalar("uint32_param", 2, "uint32", oneof="parameter_choice"),
        scalar("string_param", 3, "string", oneof="parameter_choice"),
    ],
)

LOG_SETTINGS_REQUEST = MessageSpec(
    "LogSettingsRequest", [map_field("settings", 1, "string", LOG_SETTING_VALUE)]
)
LOG_SETTINGS_RESPONSE = MessageSpec(
    "LogSettingsResponse", [map_field("settings", 1, "string", LOG_SETTING_VALUE)]
)

# ---------------------------------------------------------------------------
# service method table: method name -> (request spec, response spec)
# ---------------------------------------------------------------------------

SERVICE = "inference.GRPCInferenceService"

METHODS = {
    "ServerLive": (EMPTY, SERVER_LIVE_RESPONSE),
    "ServerReady": (EMPTY, SERVER_READY_RESPONSE),
    "ModelReady": (MODEL_READY_REQUEST, MODEL_READY_RESPONSE),
    "ServerMetadata": (EMPTY, SERVER_METADATA_RESPONSE),
    "ModelMetadata": (MODEL_METADATA_REQUEST, MODEL_METADATA_RESPONSE),
    "ModelInfer": (MODEL_INFER_REQUEST, MODEL_INFER_RESPONSE),
    "ModelStreamInfer": (MODEL_INFER_REQUEST, MODEL_STREAM_INFER_RESPONSE),  # bidi
    "ModelConfig": (MODEL_CONFIG_REQUEST, MODEL_CONFIG_RESPONSE),
    "ModelStatistics": (MODEL_STATISTICS_REQUEST, MODEL_STATISTICS_RESPONSE),
    "RepositoryIndex": (REPOSITORY_INDEX_REQUEST, REPOSITORY_INDEX_RESPONSE),
    "RepositoryModelLoad": (REPOSITORY_MODEL_LOAD_REQUEST, EMPTY),
    "RepositoryModelUnload": (REPOSITORY_MODEL_UNLOAD_REQUEST, EMPTY),
    "SystemSharedMemoryStatus": (SYSTEM_SHM_STATUS_REQUEST, SYSTEM_SHM_STATUS_RESPONSE),
    "SystemSharedMemoryRegister": (SYSTEM_SHM_REGISTER_REQUEST, EMPTY),
    "SystemSharedMemoryUnregister": (SYSTEM_SHM_UNREGISTER_REQUEST, EMPTY),
    "CudaSharedMemoryStatus": (DEVICE_SHM_STATUS_REQUEST, DEVICE_SHM_STATUS_RESPONSE),
    "CudaSharedMemoryRegister": (DEVICE_SHM_REGISTER_REQUEST, EMPTY),
    "CudaSharedMemoryUnregister": (DEVICE_SHM_UNREGISTER_REQUEST, EMPTY),
    # TPU extension rpcs (this framework's server; absent on a stock triton)
    "TpuSharedMemoryStatus": (DEVICE_SHM_STATUS_REQUEST, DEVICE_SHM_STATUS_RESPONSE),
    "TpuSharedMemoryRegister": (DEVICE_SHM_REGISTER_REQUEST, EMPTY),
    "TpuSharedMemoryUnregister": (DEVICE_SHM_UNREGISTER_REQUEST, EMPTY),
    "TraceSetting": (TRACE_SETTING_REQUEST, TRACE_SETTING_RESPONSE),
    "LogSettings": (LOG_SETTINGS_REQUEST, LOG_SETTINGS_RESPONSE),
}


def method_path(method: str) -> str:
    return f"/{SERVICE}/{method}"
