"""Synchronous KServe v2 GRPC client.

Full-surface parity with the reference's
``tritonclient.grpc.InferenceServerClient`` (grpc/_client.py:119-1936):
infer / async_infer (cancellable CallContext) / bi-di streaming with
sequence support, plus the complete admin surface — over generic grpc
callables bound to the schema-driven wire codec (no generated stubs).

TPU extensions: ``register_tpu_shared_memory`` RPCs (this framework's
server; a stock tritonserver can still be fed tpu regions through
``register_system_shared_memory`` with the region's host shm key).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import grpc

from .._base import InferenceServerClientBase, InferStat, Request, RequestTimers
from .._tensor import InferInput, InferRequestedOutput
from ..observe import TRACEPARENT_HEADER
from ..resilience import FATAL, AttemptBudget, StreamReconnected, classify_fault
from ..utils import InferenceServerException
from . import _messages as M
from ._infer import (
    InferResult,
    build_infer_request,
    from_infer_parameter,
    to_grpc_compression,
)
from ._stream import _InferStream, _ReconnectingStream
from ._wire import decode_message, encode_message

INT32_MAX = 2**31 - 1


class KeepAliveOptions:
    """GRPC keepalive configuration (maps to grpc channel args)."""

    def __init__(
        self,
        keepalive_time_ms: int = INT32_MAX,
        keepalive_timeout_ms: int = 20000,
        keepalive_permit_without_calls: bool = False,
        http2_max_pings_without_data: int = 2,
    ):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data


class CallContext:
    """Handle for an in-flight async_infer supporting cancellation."""

    def __init__(self, future: "grpc.Future"):
        self._future = future

    def cancel(self) -> bool:
        return self._future.cancel()

    def get_result(self, timeout: Optional[float] = None) -> InferResult:
        try:
            result = InferResult(self._future.result(timeout=timeout))
        except grpc.RpcError as e:
            raise _to_exception(e) from e
        try:
            # the future IS the call: stash its response metadata for
            # get_response_header parity with the unary path
            result._response_headers = _flatten_metadata(
                self._future.initial_metadata(),
                self._future.trailing_metadata())
        except Exception:
            pass
        return result


def _flatten_metadata(*metadata_pairs) -> Dict[str, str]:
    """Initial+trailing response metadata -> one ``{key: value}`` dict
    (string values only; binary ``-bin`` entries are skipped) — what the
    unary infer paths stash as ``InferResult._response_headers``."""
    out: Dict[str, str] = {}
    for pairs in metadata_pairs:
        for key, value in pairs or ():
            if isinstance(value, str):
                out[key] = value
    return out


def _to_exception(rpc_error: grpc.RpcError) -> InferenceServerException:
    code = rpc_error.code() if hasattr(rpc_error, "code") else None
    details = rpc_error.details() if hasattr(rpc_error, "details") else str(rpc_error)
    if code == grpc.StatusCode.DEADLINE_EXCEEDED:
        return InferenceServerException("Deadline Exceeded", status="StatusCode.DEADLINE_EXCEEDED")
    return InferenceServerException(
        details, status=f"StatusCode.{code.name}" if code else None
    )


class InferenceServerClient(InferenceServerClientBase):
    """Client for the KServe v2 GRPC protocol."""

    _FRONTEND = "grpc"

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional["grpc.ChannelCredentials"] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[List] = None,
    ):
        super().__init__()
        self._url = url
        self._verbose = verbose
        if channel_args is not None:
            options = list(channel_args)
        else:
            ka = keepalive_options or KeepAliveOptions()
            options = [
                ("grpc.max_send_message_length", INT32_MAX),
                ("grpc.max_receive_message_length", INT32_MAX),
                ("grpc.keepalive_time_ms", ka.keepalive_time_ms),
                ("grpc.keepalive_timeout_ms", ka.keepalive_timeout_ms),
                (
                    "grpc.keepalive_permit_without_calls",
                    int(ka.keepalive_permit_without_calls),
                ),
                (
                    "grpc.http2.max_pings_without_data",
                    ka.http2_max_pings_without_data,
                ),
            ]
        if creds is not None:
            self._channel = grpc.secure_channel(url, creds, options=options)
        elif ssl:
            rc = open(root_certificates, "rb").read() if root_certificates else None
            pk = open(private_key, "rb").read() if private_key else None
            cc = open(certificate_chain, "rb").read() if certificate_chain else None
            credentials = grpc.ssl_channel_credentials(rc, pk, cc)
            self._channel = grpc.secure_channel(url, credentials, options=options)
        else:
            self._channel = grpc.insecure_channel(url, options=options)
        self._callables: Dict[str, Callable] = {}
        self._stream: Optional[_InferStream] = None
        self._stream_span = None  # Optional[observe.StreamSpan]
        self._stream_lock = threading.Lock()
        self._infer_stat = InferStat()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self.stop_stream()
        self._channel.close()

    def __enter__(self) -> "InferenceServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def client_infer_stat(self) -> Dict[str, int]:
        return self._infer_stat.as_dict()

    # -- transport ---------------------------------------------------------
    def _callable(self, method: str, streaming: bool = False):
        cached = self._callables.get(method)
        if cached is not None:
            return cached
        req_spec, resp_spec = M.METHODS[method]
        path = M.method_path(method)
        serializer = lambda d: encode_message(req_spec, d)  # noqa: E731
        deserializer = lambda b: decode_message(resp_spec, b)  # noqa: E731
        if streaming:
            c = self._channel.stream_stream(
                path, request_serializer=serializer, response_deserializer=deserializer
            )
        else:
            c = self._channel.unary_unary(
                path, request_serializer=serializer, response_deserializer=deserializer
            )
        self._callables[method] = c
        return c

    def _metadata(self, headers: Optional[Dict[str, str]]):
        hdrs = dict(headers or {})
        request = Request(hdrs)
        self._call_plugin(request)
        return tuple(request.headers.items()) or None

    def _call(
        self,
        method: str,
        request: Dict[str, Any],
        headers: Optional[Dict[str, str]] = None,
        client_timeout: Optional[float] = None,
        compression_algorithm: Optional[str] = None,
        idempotent: bool = True,
        resilience=None,
        span=None,
        metadata_sink: Optional[Dict[str, str]] = None,
    ) -> Dict[str, Any]:
        """``metadata_sink``: when given, the call runs via ``with_call``
        and the response's initial+trailing metadata (string values only)
        land in the dict — the GRPC twin of HTTP response headers (e.g.
        ORCA's ``endpoint-load-metrics``)."""
        if self._verbose:
            print(f"{method}, metadata {headers or {}}\n{request}")
        policy = self._resilience_for(resilience)
        budget = AttemptBudget(policy, client_timeout)

        def attempt() -> Dict[str, Any]:
            attempt_timeout = budget.attempt_timeout_s(
                status="StatusCode.DEADLINE_EXCEEDED")
            try:
                if metadata_sink is None:
                    return self._callable(method)(
                        request,
                        metadata=self._metadata(headers),
                        timeout=attempt_timeout,
                        compression=to_grpc_compression(
                            compression_algorithm),
                    )
                response, call = self._callable(method).with_call(
                    request,
                    metadata=self._metadata(headers),
                    timeout=attempt_timeout,
                    compression=to_grpc_compression(compression_algorithm),
                )
                metadata_sink.clear()  # a retried attempt must not mix
                metadata_sink.update(_flatten_metadata(
                    call.initial_metadata(), call.trailing_metadata()))
                return response
            except grpc.RpcError as e:
                raise _to_exception(e) from e

        run_attempt = attempt
        on_retry = None
        if span is not None:
            def run_attempt():
                t_a = time.perf_counter_ns()
                try:
                    result = attempt()
                except BaseException:
                    span.phase("attempt", t_a, time.perf_counter_ns())
                    raise
                end = time.perf_counter_ns()
                span.phase("attempt", t_a, end)
                # unary call: send/server/first-byte are not separable, so
                # the SUCCESSFUL attempt is the ttfb window (a retried
                # request must not fold failed attempts + backoff into it)
                span.phase("ttfb", t_a, end)
                return result

            def on_retry(n, exc, delay):
                span.event("retry", attempt=n, backoff_s=round(delay, 6),
                           error=type(exc).__name__)

        if policy is None:
            response = run_attempt()
        else:
            # UNAVAILABLE/RESOURCE_EXHAUSTED re-attempt under the policy;
            # non-idempotent sequence infers only on never-sent connect
            # failures (classify_fault reads the status details)
            response = policy.execute(
                run_attempt, idempotent=idempotent, timeout_s=client_timeout,
                on_retry=on_retry)
        if self._verbose:
            print(response)
        return response

    # -- health / metadata -------------------------------------------------
    def _health(self, method, field, headers, client_timeout, probe: bool) -> bool:
        """Shared ServerLive/ServerReady call. Default: transport failures
        raise (the typed UNAVAILABLE/DEADLINE_EXCEEDED from ``_call``) so
        callers can distinguish "server said no" from "could not ask".
        ``probe=True`` maps connect/transient/timeout-class failures to
        False and bypasses the configured resilience policy — the pool's
        health poller must observe the endpoint, never a breaker fast-fail."""
        try:
            resp = self._call(method, {}, headers, client_timeout,
                              resilience=False if probe else None)
        except InferenceServerException as e:
            if probe and classify_fault(e) != FATAL:
                return False
            raise
        return bool(resp.get(field, False))

    def is_server_live(self, headers=None, client_timeout=None,
                       probe: bool = False) -> bool:
        return self._health("ServerLive", "live", headers, client_timeout, probe)

    def is_server_ready(self, headers=None, client_timeout=None,
                        probe: bool = False) -> bool:
        return self._health("ServerReady", "ready", headers, client_timeout, probe)

    def is_model_ready(self, model_name, model_version="", headers=None, client_timeout=None) -> bool:
        # transport errors propagate (matching the HTTP client and the
        # reference); a served-but-unknown model comes back ready=False
        req = {"name": model_name, "version": model_version}
        return bool(self._call("ModelReady", req, headers, client_timeout).get("ready", False))

    def get_server_metadata(self, headers=None, client_timeout=None, as_json=True) -> Dict[str, Any]:
        # as_json accepted for reference-signature compat; results are always
        # dicts here (there is no protobuf message object to return)
        return self._call("ServerMetadata", {}, headers, client_timeout)

    def get_model_metadata(
        self, model_name, model_version="", headers=None, client_timeout=None,
        as_json=True,
    ) -> Dict[str, Any]:
        metadata = self._call(
            "ModelMetadata", {"name": model_name, "version": model_version},
            headers, client_timeout,
        )
        # captured into the integrity contract cache: later responses
        # are validated against this fetched truth (never vice versa)
        self._integrity_note_metadata(model_name, metadata)
        return metadata

    def get_model_config(
        self, model_name, model_version="", headers=None, client_timeout=None,
        as_json=True,
    ) -> Dict[str, Any]:
        return self._call(
            "ModelConfig", {"name": model_name, "version": model_version},
            headers, client_timeout,
        )

    # -- repository --------------------------------------------------------
    def get_model_repository_index(self, headers=None, client_timeout=None) -> List[Dict[str, Any]]:
        resp = self._call("RepositoryIndex", {}, headers, client_timeout)
        return resp.get("models", [])

    def load_model(
        self, model_name, headers=None, config: Optional[str] = None,
        files: Optional[Dict[str, bytes]] = None, client_timeout=None,
    ) -> None:
        params: Dict[str, Any] = {}
        if config is not None:
            params["config"] = {"string_param": config}
        for path, content in (files or {}).items():
            params[path] = {"bytes_param": content}
        req: Dict[str, Any] = {"model_name": model_name}
        if params:
            req["parameters"] = params
        self._call("RepositoryModelLoad", req, headers, client_timeout)

    def unload_model(
        self, model_name, headers=None, unload_dependents: bool = False, client_timeout=None
    ) -> None:
        req = {
            "model_name": model_name,
            "parameters": {"unload_dependents": {"bool_param": unload_dependents}},
        }
        self._call("RepositoryModelUnload", req, headers, client_timeout)

    # -- statistics / trace / log ------------------------------------------
    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, client_timeout=None,
        as_json=True,
    ) -> Dict[str, Any]:
        return self._call(
            "ModelStatistics", {"name": model_name, "version": model_version},
            headers, client_timeout,
        )

    def update_trace_settings(
        self, model_name=None, settings: Optional[Dict[str, Any]] = None,
        headers=None, client_timeout=None,
    ) -> Dict[str, Any]:
        req: Dict[str, Any] = {"settings": {}}
        if model_name:
            req["model_name"] = model_name
        for key, value in (settings or {}).items():
            if value is None:
                req["settings"][key] = {}
            elif isinstance(value, (list, tuple)):
                req["settings"][key] = {"value": [str(v) for v in value]}
            else:
                req["settings"][key] = {"value": [str(value)]}
        resp = self._call("TraceSetting", req, headers, client_timeout)
        return {k: v.get("value", []) for k, v in resp.get("settings", {}).items()}

    def get_trace_settings(self, model_name=None, headers=None, client_timeout=None) -> Dict[str, Any]:
        req = {"model_name": model_name} if model_name else {}
        resp = self._call("TraceSetting", req, headers, client_timeout)
        return {k: v.get("value", []) for k, v in resp.get("settings", {}).items()}

    def update_log_settings(self, settings: Dict[str, Any], headers=None, client_timeout=None) -> Dict[str, Any]:
        req: Dict[str, Any] = {"settings": {}}
        for key, value in settings.items():
            if isinstance(value, bool):
                req["settings"][key] = {"bool_param": value}
            elif isinstance(value, int):
                req["settings"][key] = {"uint32_param": value}
            else:
                req["settings"][key] = {"string_param": str(value)}
        resp = self._call("LogSettings", req, headers, client_timeout)
        return {k: from_infer_parameter(v) for k, v in resp.get("settings", {}).items()}

    def get_log_settings(self, headers=None, client_timeout=None) -> Dict[str, Any]:
        resp = self._call("LogSettings", {}, headers, client_timeout)
        return {k: from_infer_parameter(v) for k, v in resp.get("settings", {}).items()}

    # -- shared memory -----------------------------------------------------
    def get_system_shared_memory_status(
        self, region_name="", headers=None, client_timeout=None
    ) -> List[Dict[str, Any]]:
        resp = self._call(
            "SystemSharedMemoryStatus", {"name": region_name}, headers, client_timeout
        )
        return list(resp.get("regions", {}).values())

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ) -> None:
        self._shm_call(
            "system", "register", self._call,
            "SystemSharedMemoryRegister",
            {"name": name, "key": key, "offset": offset, "byte_size": byte_size},
            headers, client_timeout,
        )

    def unregister_system_shared_memory(self, name="", headers=None, client_timeout=None) -> None:
        self._shm_call(
            "system", "unregister", self._call,
            "SystemSharedMemoryUnregister", {"name": name}, headers,
            client_timeout, region_name=name)

    def _device_shm_status(self, method, region_name, headers, client_timeout):
        resp = self._call(method, {"name": region_name}, headers, client_timeout)
        return list(resp.get("regions", {}).values())

    def _device_shm_register(self, method, name, raw_handle, device_id, byte_size, headers, client_timeout):
        if isinstance(raw_handle, str):
            raw_handle = raw_handle.encode("ascii")
        self._shm_call(
            "cuda" if method.startswith("Cuda") else "tpu", "register",
            self._call,
            method,
            {
                "name": name,
                "raw_handle": raw_handle,
                "device_id": device_id,
                "byte_size": byte_size,
            },
            headers, client_timeout,
        )

    def get_cuda_shared_memory_status(self, region_name="", headers=None, client_timeout=None):
        return self._device_shm_status("CudaSharedMemoryStatus", region_name, headers, client_timeout)

    def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ) -> None:
        self._device_shm_register(
            "CudaSharedMemoryRegister", name, raw_handle, device_id, byte_size, headers, client_timeout
        )

    def unregister_cuda_shared_memory(self, name="", headers=None, client_timeout=None) -> None:
        self._shm_call(
            "cuda", "unregister", self._call,
            "CudaSharedMemoryUnregister", {"name": name}, headers,
            client_timeout)

    def get_tpu_shared_memory_status(self, region_name="", headers=None, client_timeout=None):
        return self._device_shm_status("TpuSharedMemoryStatus", region_name, headers, client_timeout)

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ) -> None:
        """Register a tpu_shared_memory region by its base64 raw handle."""
        self._device_shm_register(
            "TpuSharedMemoryRegister", name, raw_handle, device_id, byte_size, headers, client_timeout
        )

    def unregister_tpu_shared_memory(self, name="", headers=None, client_timeout=None) -> None:
        self._shm_call(
            "tpu", "unregister", self._call,
            "TpuSharedMemoryUnregister", {"name": name}, headers,
            client_timeout, region_name=name)

    # -- inference ---------------------------------------------------------
    def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        parameters: Optional[Dict[str, Any]] = None,
        compression_algorithm: Optional[str] = None,
        resilience=None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        span = self._obs_begin(self._FRONTEND, model_name)
        if span is not None and tenant is not None:
            # client-side QoS attribution only (see client_tpu.tenancy);
            # the tenant is never sent on the wire
            span.event("tenant", tenant=tenant)
        timers = RequestTimers()
        timers.capture(RequestTimers.REQUEST_START)
        actx = None
        try:
            # arena data plane: promote staged binary inputs into leased
            # slabs and ensure (cached) region registrations BEFORE the
            # request is built, so it rides shm params
            actx = self._arena_bind(inputs, outputs)
            request = build_infer_request(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
            )
            # unconditional like HTTP: ORCA opt-in must not depend on
            # whether this request got a span
            hdrs = self._orca_opt_in(dict(headers or {}))
            if span is not None:
                hdrs[TRACEPARENT_HEADER] = span.traceparent()
                span.phase("serialize", span.start_ns,
                           time.perf_counter_ns())
            timers.capture(RequestTimers.SEND_START)
            metadata_sink: Dict[str, str] = {}
            response = self._call(
                "ModelInfer", request, hdrs, client_timeout, compression_algorithm,
                idempotent=sequence_id == 0, resilience=resilience, span=span,
                metadata_sink=metadata_sink,
            )
            timers.capture(RequestTimers.SEND_END)
            timers.capture(RequestTimers.RECV_START)
            result = InferResult(response)
            result._response_headers = metadata_sink
            if actx is not None:
                actx.finish(result)
            # contract validation: the result never reaches the caller
            # (nor the ORCA path below) un-checked
            self._integrity_check(result, inputs, outputs, request_id,
                                  model_name)
            timers.capture(RequestTimers.RECV_END)
        except BaseException as e:
            if span is not None:
                self._telemetry.finish(span, error=e)
            raise
        finally:
            if actx is not None:
                actx.settle()
        timers.capture(RequestTimers.REQUEST_END)
        self._infer_stat.update(timers)
        if span is not None:
            span.phase("deserialize",
                       timers.get(RequestTimers.RECV_START),
                       timers.get(RequestTimers.RECV_END))
            self._telemetry.finish(span)
        # after the phase capture: ORCA bookkeeping (header parse + gauge
        # writes) must not masquerade as recv/deserialize milliseconds
        self._orca_ingest(result)
        return result

    def async_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        callback: Optional[Callable] = None,
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        parameters: Optional[Dict[str, Any]] = None,
        compression_algorithm: Optional[str] = None,
    ) -> CallContext:
        """Fire an async inference; ``callback(result, error)`` when done."""
        # ensure-only arena binding: registrations are cached per endpoint;
        # promotion is skipped because a transient lease could be reused
        # before the server reads it (the future outlives this call)
        self._arena_bind(inputs, outputs, promote=False)
        request = build_infer_request(
            model_name, inputs, model_version, outputs, request_id,
            sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
        )
        future = self._callable("ModelInfer").future(
            request,
            metadata=self._metadata(self._orca_opt_in(dict(headers or {}))),
            timeout=client_timeout,
            compression=to_grpc_compression(compression_algorithm),
        )
        context = CallContext(future)
        if callback is not None:
            def _done(f):
                result, error = None, None
                try:
                    result = InferResult(f.result())
                    try:
                        # the future IS the call: stash response metadata
                        # for get_response_header parity with the unary
                        # path (and feed any ORCA header to telemetry)
                        result._response_headers = _flatten_metadata(
                            f.initial_metadata(), f.trailing_metadata())
                        self._orca_ingest(result)
                    except Exception:
                        pass
                    # same contract check as the unary path: a violation
                    # becomes the callback's typed error, never a result
                    try:
                        self._integrity_check(result, inputs, outputs,
                                              request_id, model_name)
                    except InferenceServerException as e:
                        result, error = None, e
                except grpc.RpcError as e:
                    error = _to_exception(e)
                except Exception as e:  # cancelled etc.
                    error = InferenceServerException(str(e))
                # outside the try: a raising user callback must not be
                # re-invoked with a phantom error
                callback(result, error)

            future.add_done_callback(_done)
        return context

    # -- streaming ---------------------------------------------------------
    def start_stream(
        self,
        callback: Callable,
        stream_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
        auto_reconnect: bool = False,
        resilience=None,
    ) -> None:
        """Open the bidi stream; ``callback(result, error)`` per response.

        ``auto_reconnect=True`` (requires a resilience policy with a
        RetryPolicy, configured on the client or passed here) makes the
        stream survive transport death: the bidi call is re-established
        with backoff and the callback receives a
        ``resilience.StreamReconnected`` event (as the result). In-flight
        idempotent requests are re-sent; in-flight sequence requests are
        NEVER silently re-sent — their ids arrive in the event's
        ``abandoned_request_ids`` (see docs/resilience.md)."""
        with self._stream_lock:
            if self._stream is not None:
                raise InferenceServerException(
                    "cannot start a stream: one is already active; stop it first"
                )
            span = self._obs_begin_stream(self._FRONTEND, "", op="stream")
            self._stream_span = span
            if span is not None:
                # stream-level traceparent: every request on the bidi call
                # joins this stream's trace in the server access records,
                # and it survives reconnects (metadata is recomputed per
                # re-open from this same headers dict)
                headers = dict(headers or {})
                headers[TRACEPARENT_HEADER] = span.traceparent()
                user_callback = callback
                mark = span.mark
                tel_ = self._telemetry
                stream_box: Dict[str, Any] = {}

                def callback(result, error):
                    # per-response hot path: one branch + one mark; the
                    # rare paths (reconnect sub-span, error event) stay off
                    # the token lane
                    if error is not None:
                        span.event("stream_error",
                                   error=type(error).__name__)
                        # in-band per-request errors leave the bidi call
                        # healthy; a TERMINAL error (the stream died and
                        # won't reconnect) must close the span with the
                        # error now — stop_stream may never be called, and
                        # its error-less finish would count a clean stream
                        inner = stream_box.get("stream")
                        if inner is None or not inner.is_active():
                            tel_.finish_stream(span, error=error)
                    elif type(result) is StreamReconnected:
                        span.reconnect(
                            abandoned=len(result.abandoned_request_ids),
                            resent=len(result.resent_request_ids))
                    else:
                        mark()
                    user_callback(result, error)

            compression = to_grpc_compression(compression_algorithm)
            try:
                if auto_reconnect:
                    def open_inner(cb):
                        inner = _InferStream(cb, self._verbose)
                        # metadata computed per (re)open: the registered
                        # plugin must re-stamp auth headers on every
                        # reconnect, or an hours-later reconnect goes out
                        # with an expired token
                        inner.start(
                            self._callable("ModelStreamInfer", streaming=True),
                            self._metadata(headers), stream_timeout,
                            compression=compression,
                        )
                        return inner

                    stream = _ReconnectingStream(
                        open_inner, callback, self._resilience_for(resilience),
                        self._verbose,
                    )
                    stream.start()
                else:
                    stream = _InferStream(callback, self._verbose)
                    stream.start(
                        self._callable("ModelStreamInfer", streaming=True),
                        self._metadata(headers), stream_timeout,
                        compression=compression,
                    )
            except BaseException as e:
                if span is not None and self._telemetry is not None:
                    self._telemetry.finish_stream(span, error=e)
                raise
            if span is not None:
                stream_box["stream"] = stream
            self._stream = stream

    def async_stream_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        enable_empty_final_response: bool = False,
        parameters: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Send one request on the open stream (sequences, decoupled models)."""
        with self._stream_lock:
            stream = self._stream
        if stream is None:
            raise InferenceServerException("stream not available: call start_stream first")
        # ensure-only arena binding: a stream request may be a region's
        # FIRST use against this endpoint (no promotion: the stream
        # outlives this call, so a transient lease could be reused before
        # the server reads it)
        self._arena_bind(inputs, outputs, promote=False)
        request = build_infer_request(
            model_name, inputs, model_version, outputs, request_id,
            sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
        )
        if enable_empty_final_response:
            request.setdefault("parameters", {})[
                "triton_enable_empty_final_response"
            ] = {"bool_param": True}
        # sequence requests carry server-side state transitions and must
        # never be silently re-sent by a reconnecting stream
        stream.enqueue(request, idempotent=sequence_id == 0)

    def stop_stream(self, cancel_requests: bool = False) -> None:
        with self._stream_lock:
            stream, self._stream = self._stream, None
            # the span attribute survives the stop for post-hoc inspection
            # (stream_span()); a new start_stream replaces it
            span = self._stream_span
        if stream is not None:
            stream.close(cancel_requests)
        tel = self._telemetry
        if span is not None and tel is not None:
            tel.finish_stream(span)

    def stream_span(self):
        """The active (or most recently stopped) stream's StreamSpan —
        None without telemetry. Harnesses read TTFT/inter-chunk marks from
        it instead of re-measuring with their own stopwatch."""
        return self._stream_span
