"""Bi-directional streaming machinery for GRPC inference.

Parity with the reference's ``grpc/_infer_stream.py`` (:39-191): a request
queue drained by a ``_RequestIterator`` feeding the bidi call, and a reader
thread dispatching ``callback(result, error)`` per response. Stream death
marks the stream inactive; a new stream must be started — unless the
client opened the stream with ``auto_reconnect=True``, in which case
:class:`_ReconnectingStream` re-establishes the bidi call under the
client's resilience policy and surfaces a typed
``resilience.StreamReconnected`` event through the callback.
"""

from __future__ import annotations

import itertools
import queue
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

import grpc

from ..resilience import StreamReconnected
from ..utils import InferenceServerException
from ._infer import InferResult


class _RequestIterator:
    """Blocking iterator over enqueued request dicts; ``None`` closes it."""

    def __init__(self):
        self._queue: "queue.Queue" = queue.Queue()

    def put(self, request: Optional[Dict[str, Any]]) -> None:
        self._queue.put(request)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is None:
            raise StopIteration
        return item


class _InferStream:
    """One live bidi ModelStreamInfer call."""

    def __init__(self, callback: Callable[[Optional[InferResult], Optional[Exception]], None], verbose: bool = False):
        self._callback = callback
        self._verbose = verbose
        self._requests = _RequestIterator()
        self._call = None
        self._reader: Optional[threading.Thread] = None
        self._active = True
        self._lock = threading.Lock()

    def start(self, stream_callable, metadata, timeout, compression=None) -> None:
        self._call = stream_callable(
            self._requests, metadata=metadata, timeout=timeout,
            compression=compression,
        )
        self._reader = threading.Thread(
            target=self._read_loop, name="client_tpu_grpc_stream", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            for response in self._call:
                err_msg = response.get("error_message")
                if err_msg:
                    error = InferenceServerException(err_msg)
                    # servers may attach the failing request's id in the
                    # otherwise-empty infer_response; expose it so a
                    # reconnecting wrapper can retire the exact pending
                    # entry instead of guessing by order
                    rid = response.get("infer_response", {}).get("id")
                    if rid:
                        error.request_id = rid
                    self._callback(None, error)
                    continue
                result = InferResult(response.get("infer_response", {}))
                if self._verbose:
                    print(result.get_response())
                self._callback(result, None)
        except grpc.RpcError as rpc_error:
            # Reference grpc/_infer_stream.py:157-167: refresh the active
            # state and deliver the true grpc status to the callback —
            # CANCELLED included — so triton_grpc_error-mode users see real
            # status codes (StatusCode.CANCELLED / StatusCode.UNAVAILABLE).
            with self._lock:
                self._active = False
            code = rpc_error.code() if hasattr(rpc_error, "code") else None
            details = (
                rpc_error.details() if hasattr(rpc_error, "details") else str(rpc_error)
            )
            if code == grpc.StatusCode.CANCELLED:
                error = InferenceServerException(
                    details or "Locally cancelled by application!",
                    status="StatusCode.CANCELLED",
                )
            else:
                error = InferenceServerException(
                    details or f"stream closed: {rpc_error}",
                    status=f"StatusCode.{code.name}" if code else None,
                )
            self._callback(None, error)
        except Exception as e:  # defensive: never kill the thread silently
            with self._lock:
                self._active = False
            self._callback(None, InferenceServerException(f"stream failure: {e}"))

    def is_active(self) -> bool:
        with self._lock:
            return self._active

    def enqueue(self, request: Dict[str, Any], idempotent: bool = True) -> None:
        # ``idempotent`` is meaningful for _ReconnectingStream (same
        # signature so the client treats both stream kinds uniformly)
        if not self.is_active():
            raise InferenceServerException(
                "the stream is no longer in a valid state; start a new stream"
            )
        self._requests.put(request)

    def close(self, cancel_requests: bool = False) -> None:
        if cancel_requests and self._call is not None:
            self._call.cancel()
        self._requests.put(None)
        if self._reader is not None:
            self._reader.join(timeout=30)
            self._reader = None
        with self._lock:
            self._active = False


class _PendingRequest:
    """One in-flight stream request tracked for reconnect accounting."""

    __slots__ = ("request", "idempotent", "sent")

    def __init__(self, request: Dict[str, Any], idempotent: bool):
        self.request = request
        self.idempotent = idempotent
        self.sent = False  # placed on a live stream's request queue


class _ReconnectingStream:
    """A bidi stream that survives transport death.

    Wraps ``_InferStream``: every enqueued request is tracked until a
    response with its id arrives (requests without an id get an
    auto-assigned ``_ctpu_rc_N`` — the server echoes it back). When the
    inner stream dies with a retryable fault, a new bidi call is opened
    after the policy's backoff and the callback receives a
    ``StreamReconnected`` event (as the result, ``error=None``). In-flight
    idempotent requests are transparently re-sent in order; in-flight
    NON-idempotent requests (sequence infers: the server may already have
    applied their state transition) are NEVER silently re-sent — their ids
    arrive in ``StreamReconnected.abandoned_request_ids`` and the
    application owns re-driving the sequence.

    Decoupled caveat: a request's pending entry is retired at its final
    response (``triton_final_response``; absent means unary-per-request),
    so a decoupled generation interrupted mid-stream is re-issued from the
    start if idempotent, never resumed from the middle.
    """

    def __init__(self, open_fn: Callable[[Callable], _InferStream],
                 callback: Callable, policy, verbose: bool = False):
        if policy is None or policy.retry is None:
            raise InferenceServerException(
                "auto_reconnect requires a resilience policy with a RetryPolicy"
            )
        self._open_fn = open_fn
        self._callback = callback
        self._policy = policy
        self._verbose = verbose
        self._lock = threading.Lock()
        self._pending: "OrderedDict[str, _PendingRequest]" = OrderedDict()
        self._auto_id = itertools.count(1)
        self._closed = False
        self._dead = False
        self._closing = threading.Event()  # wakes a sleeping backoff
        self._inner: Optional[_InferStream] = None
        self._attempt = 0  # consecutive reconnects without a response

    def start(self) -> None:
        self._inner = self._open_fn(self._on_event)

    def is_active(self) -> bool:
        with self._lock:
            if self._closed or self._dead:
                return False
        inner = self._inner
        return inner is not None and inner.is_active()

    def enqueue(self, request: Dict[str, Any], idempotent: bool = True) -> None:
        with self._lock:
            if self._closed or self._dead:
                raise InferenceServerException(
                    "the stream is no longer in a valid state; start a new stream"
                )
            rid = request.get("id")
            if not rid:
                rid = f"_ctpu_rc_{next(self._auto_id)}"
                request["id"] = rid
            pending = _PendingRequest(request, idempotent)
            # sent is marked BEFORE the put: once the request is on the live
            # queue the gRPC sender may transmit it immediately, and a
            # reconnect racing this thread must err toward "may have reached
            # the server" (abandon) — never toward a silent re-send
            pending.sent = True
            self._pending[rid] = pending
            inner = self._inner
        try:
            inner.enqueue(request)
        except InferenceServerException:
            # the inner stream died before the put: the request provably
            # never left this process. Downgrade sent only if no reconnect
            # has intervened — a racing reconnect may already have
            # snapshotted (or re-sent) this entry, and a late sent=False
            # would schedule a duplicate send at the next reconnect.
            with self._lock:
                if self._inner is inner and rid in self._pending:
                    pending.sent = False

    def close(self, cancel_requests: bool = False) -> None:
        with self._lock:
            self._closed = True
            inner = self._inner
        self._closing.set()  # interrupt a reader thread mid-backoff
        if inner is not None:
            inner.close(cancel_requests)

    # -- event path (runs on the inner stream's reader thread) --------------
    def _on_event(self, result: Optional[InferResult], error) -> None:
        if error is None:
            resp = result.get_response() if result is not None else {}
            rid = resp.get("id")
            tfr = resp.get("parameters", {}).get("triton_final_response")
            final = True if tfr is None else bool(tfr.get("bool_param", False))
            with self._lock:
                if rid and final:
                    self._pending.pop(rid, None)
                self._attempt = 0  # the transport is demonstrably healthy
            self._callback(result, None)
            return
        inner = self._inner
        if inner is not None and inner.is_active():
            # per-request in-band error (_read_loop dispatched an
            # error_message response and kept reading): the bidi call is
            # healthy — surface the error, do NOT tear down or reconnect.
            # Retire the errored request's pending entry: exactly, when the
            # server attached its id (this framework's server does); else
            # the OLDEST sent entry (requests are processed in order). A
            # mis-retire errs fail-safe — at worst a request is NOT re-sent
            # after a reconnect, never double-applied — and pending cannot
            # grow unboundedly on an error-heavy stream.
            rid = getattr(error, "request_id", None)
            with self._lock:
                if rid is None:
                    rid = next(
                        (r for r, p in self._pending.items() if p.sent), None)
                if rid is not None:
                    self._pending.pop(rid, None)
            self._callback(None, error)
            return
        with self._lock:
            if self._closed:
                give_up = True  # user-initiated teardown: pass through
            else:
                domain = self._policy.classify(error)
                retry = self._policy.retry
                # idempotent=True: request-level idempotency is handled by
                # the resend/abandon split below, so only the policy's
                # domain gates decide whether the STREAM comes back (e.g.
                # retry_timeouts=False keeps stream_timeout terminal)
                give_up = (
                    not retry.retries_domain(domain, True)
                    or self._attempt + 1 >= retry.max_attempts
                )
            if give_up:
                self._dead = True
        if give_up:
            self._callback(None, error)
            return
        delay = retry.backoff_s(self._attempt)
        if self._verbose:
            print(f"stream died ({error}); reconnecting in {delay:.3f}s")
        # interruptible: close() must not wait out a long backoff
        self._closing.wait(delay)
        with self._lock:
            if self._closed:  # torn down during the backoff sleep
                self._dead = True
                return
        try:
            new_inner = self._open_fn(self._on_event)
        except Exception as e:  # channel-level failure opening the call
            with self._lock:
                self._dead = True
            self._callback(None, InferenceServerException(
                f"stream reconnect failed: {e}"))
            return
        with self._lock:
            if self._closed:  # close() raced the open: tear the call down
                self._dead = True
                closed_late = True
                self._pending.clear()
            else:
                closed_late = False
                self._attempt += 1
                attempt = self._attempt
                # swap + snapshot in ONE critical section: a concurrent
                # enqueue() is either in the snapshot (added before this
                # block) or targets new_inner (added after) — never both.
                # An enqueue racing the dead inner's put is handled on its
                # side: the sent=False downgrade applies only if no
                # reconnect intervened, so the failure direction here is
                # abandon/fail-safe (a sequence request that never left the
                # process may be reported abandoned), never a double-apply.
                self._inner = new_inner
                resend, abandoned = [], []
                for rid, pending in list(self._pending.items()):
                    if pending.sent and not pending.idempotent:
                        # may have reached the server: re-sending could
                        # apply a sequence state transition twice —
                        # surface, don't send
                        abandoned.append(rid)
                        del self._pending[rid]
                    else:
                        resend.append(pending)
        if closed_late:
            new_inner.close()
            return
        event = StreamReconnected(
            attempt=attempt,
            resent_request_ids=[p.request["id"] for p in resend],
            abandoned_request_ids=abandoned,
            cause=error,
        )
        observer = getattr(self._policy, "observer", None)
        if observer is not None:
            # exactly-once telemetry bridge: the observer (observe.
            # Telemetry) counts the reconnect + abandoned sequences here,
            # BEFORE the user callback can swallow or re-raise on the
            # event; the traced callback only annotates the span
            try:
                observer.on_stream_reconnect(event)
            except TypeError:
                # duck-typed observer protocol: a pre-event observer takes
                # no arguments — its reconnect accounting must keep firing
                try:
                    observer.on_stream_reconnect()
                except Exception:
                    pass
            except Exception:
                pass
        # event BEFORE the resends hit the wire: the app learns which ids
        # are being re-sent before the new reader thread can deliver any of
        # their responses (the new stream carries no requests until below)
        self._callback(event, None)
        for pending in resend:
            pending.sent = True  # on the wire the instant the put lands
            try:
                new_inner.enqueue(pending.request)
            except InferenceServerException:
                pending.sent = False  # never left: the next reconnect resends
