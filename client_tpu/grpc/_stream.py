"""Bi-directional streaming machinery for GRPC inference.

Parity with the reference's ``grpc/_infer_stream.py`` (:39-191): a request
queue drained by a ``_RequestIterator`` feeding the bidi call, and a reader
thread dispatching ``callback(result, error)`` per response. Stream death
marks the stream inactive; a new stream must be started.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional

import grpc

from ..utils import InferenceServerException
from ._infer import InferResult


class _RequestIterator:
    """Blocking iterator over enqueued request dicts; ``None`` closes it."""

    def __init__(self):
        self._queue: "queue.Queue" = queue.Queue()

    def put(self, request: Optional[Dict[str, Any]]) -> None:
        self._queue.put(request)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is None:
            raise StopIteration
        return item


class _InferStream:
    """One live bidi ModelStreamInfer call."""

    def __init__(self, callback: Callable[[Optional[InferResult], Optional[Exception]], None], verbose: bool = False):
        self._callback = callback
        self._verbose = verbose
        self._requests = _RequestIterator()
        self._call = None
        self._reader: Optional[threading.Thread] = None
        self._active = True
        self._lock = threading.Lock()

    def start(self, stream_callable, metadata, timeout, compression=None) -> None:
        self._call = stream_callable(
            self._requests, metadata=metadata, timeout=timeout,
            compression=compression,
        )
        self._reader = threading.Thread(
            target=self._read_loop, name="client_tpu_grpc_stream", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            for response in self._call:
                err_msg = response.get("error_message")
                if err_msg:
                    self._callback(None, InferenceServerException(err_msg))
                    continue
                result = InferResult(response.get("infer_response", {}))
                if self._verbose:
                    print(result.get_response())
                self._callback(result, None)
        except grpc.RpcError as rpc_error:
            # Reference grpc/_infer_stream.py:157-167: refresh the active
            # state and deliver the true grpc status to the callback —
            # CANCELLED included — so triton_grpc_error-mode users see real
            # status codes (StatusCode.CANCELLED / StatusCode.UNAVAILABLE).
            with self._lock:
                self._active = False
            code = rpc_error.code() if hasattr(rpc_error, "code") else None
            details = (
                rpc_error.details() if hasattr(rpc_error, "details") else str(rpc_error)
            )
            if code == grpc.StatusCode.CANCELLED:
                error = InferenceServerException(
                    details or "Locally cancelled by application!",
                    status="StatusCode.CANCELLED",
                )
            else:
                error = InferenceServerException(
                    details or f"stream closed: {rpc_error}",
                    status=f"StatusCode.{code.name}" if code else None,
                )
            self._callback(None, error)
        except Exception as e:  # defensive: never kill the thread silently
            with self._lock:
                self._active = False
            self._callback(None, InferenceServerException(f"stream failure: {e}"))

    def is_active(self) -> bool:
        with self._lock:
            return self._active

    def enqueue(self, request: Dict[str, Any]) -> None:
        if not self.is_active():
            raise InferenceServerException(
                "the stream is no longer in a valid state; start a new stream"
            )
        self._requests.put(request)

    def close(self, cancel_requests: bool = False) -> None:
        if cancel_requests and self._call is not None:
            self._call.cancel()
        self._requests.put(None)
        if self._reader is not None:
            self._reader.join(timeout=30)
            self._reader = None
        with self._lock:
            self._active = False
