"""Schema-driven protobuf wire-format codec (proto3 semantics).

The GRPC protocol surface is implemented without generated stubs: messages
are plain Python dicts encoded/decoded against declarative field specs
(see ``_messages.py``). This keeps the framework free of a protoc build
step, makes the raw-tensor path (``raw_input_contents``) a zero-copy chunk
append, and sidesteps the protobuf-python object graph entirely.

Wire format notes (developers.google.com/protocol-buffers/docs/encoding):
- tag = (field_number << 3) | wire_type; wire types: 0 varint, 1 fixed64,
  2 length-delimited, 5 fixed32.
- proto3 scalars at their default value are not emitted.
- repeated numeric fields are packed (wire type 2) on encode; both packed
  and unpacked forms are accepted on decode.
- map<K,V> fields are repeated messages with key=1, value=2.
- int32/int64 negatives are 10-byte two's-complement varints.
- Unknown fields are skipped on decode (forward compatibility).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple, Union

# ---------------------------------------------------------------------------
# varint primitives
# ---------------------------------------------------------------------------


def encode_varint(value: int, out: List[bytes]) -> None:
    if value < 0:
        value += 1 << 64
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(bytes((byte | 0x80,)))
        else:
            out.append(bytes((byte,)))
            return


def decode_varint(buf, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _signed(value: int) -> int:
    return value - (1 << 64) if value >= 1 << 63 else value


# ---------------------------------------------------------------------------
# field specs
# ---------------------------------------------------------------------------

_VARINT_KINDS = frozenset(("int32", "int64", "uint32", "uint64", "bool", "enum"))
_WIRE_OF_KIND = {
    "double": 1,
    "float": 5,
    "string": 2,
    "bytes": 2,
    "message": 2,
}


class Field:
    __slots__ = ("name", "num", "kind", "repeated", "msg", "map_kv", "oneof")

    def __init__(
        self,
        name: str,
        num: int,
        kind: str,
        repeated: bool = False,
        msg: Optional["MessageSpec"] = None,
        map_kv: Optional[Tuple["Field", "Field"]] = None,
        oneof: Optional[str] = None,
    ):
        self.name = name
        self.num = num
        self.kind = kind  # scalar kind | 'message' | 'map'
        self.repeated = repeated
        self.msg = msg
        self.map_kv = map_kv
        self.oneof = oneof


class MessageSpec:
    """An ordered collection of Fields; encode/decode plain dicts against it."""

    def __init__(self, name: str, fields: Optional[List[Field]] = None):
        self.name = name
        self.fields: List[Field] = []
        self.by_num: Dict[int, Field] = {}
        self.by_name: Dict[str, Field] = {}
        for f in fields or []:
            self.add(f)

    def add(self, field: Field) -> "MessageSpec":
        self.fields.append(field)
        self.by_num[field.num] = field
        self.by_name[field.name] = field
        return self


# convenience constructors used by _messages.py
def scalar(name: str, num: int, kind: str, repeated: bool = False, oneof: str = None) -> Field:
    return Field(name, num, kind, repeated=repeated, oneof=oneof)


def message(name: str, num: int, spec: MessageSpec, repeated: bool = False, oneof: str = None) -> Field:
    return Field(name, num, "message", repeated=repeated, msg=spec, oneof=oneof)


def map_field(name: str, num: int, key_kind: str, value: Union[str, MessageSpec]) -> Field:
    if isinstance(value, MessageSpec):
        vfield = Field("value", 2, "message", msg=value)
    else:
        vfield = Field("value", 2, value)
    return Field(name, num, "map", map_kv=(Field("key", 1, key_kind), vfield))


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------


def _encode_tag(num: int, wire_type: int, out: List[bytes]) -> None:
    encode_varint((num << 3) | wire_type, out)


def _encode_scalar(f: Field, value: Any, out: List[bytes]) -> None:
    kind = f.kind
    if kind in _VARINT_KINDS:
        _encode_tag(f.num, 0, out)
        encode_varint(int(value), out)
    elif kind == "double":
        _encode_tag(f.num, 1, out)
        out.append(struct.pack("<d", value))
    elif kind == "float":
        _encode_tag(f.num, 5, out)
        out.append(struct.pack("<f", value))
    elif kind == "string":
        raw = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        _encode_tag(f.num, 2, out)
        encode_varint(len(raw), out)
        out.append(raw)
    elif kind == "bytes":
        raw = value if isinstance(value, (bytes, memoryview, bytearray)) else bytes(value)
        _encode_tag(f.num, 2, out)
        encode_varint(len(raw), out)
        out.append(bytes(raw) if not isinstance(raw, bytes) else raw)
    else:
        raise ValueError(f"cannot encode scalar kind {kind}")


def _encode_packed(f: Field, values, out: List[bytes]) -> None:
    inner: List[bytes] = []
    for v in values:
        if f.kind in _VARINT_KINDS:
            encode_varint(int(v), inner)
        elif f.kind == "double":
            inner.append(struct.pack("<d", v))
        elif f.kind == "float":
            inner.append(struct.pack("<f", v))
        else:
            raise ValueError(f"kind {f.kind} is not packable")
    payload = b"".join(inner)
    _encode_tag(f.num, 2, out)
    encode_varint(len(payload), out)
    out.append(payload)


def encode_message(spec: MessageSpec, value: Dict[str, Any]) -> bytes:
    """Encode dict ``value`` against ``spec``; returns the serialized bytes."""
    out: List[bytes] = []
    for f in spec.fields:
        v = value.get(f.name)
        if v is None:
            continue
        if f.kind == "map":
            kf, vf = f.map_kv
            for mk, mv in v.items():
                entry: List[bytes] = []
                _encode_map_entry(kf, vf, mk, mv, entry)
                payload = b"".join(entry)
                _encode_tag(f.num, 2, out)
                encode_varint(len(payload), out)
                out.append(payload)
        elif f.kind == "message":
            items = v if f.repeated else [v]
            for item in items:
                payload = encode_message(f.msg, item)
                _encode_tag(f.num, 2, out)
                encode_varint(len(payload), out)
                out.append(payload)
        elif f.repeated:
            if not len(v):
                continue
            if f.kind in _VARINT_KINDS or f.kind in ("float", "double"):
                _encode_packed(f, v, out)
            else:
                for item in v:
                    _encode_scalar(f, item, out)
        else:
            # proto3: skip default values — except oneof members, which have
            # explicit presence and must serialize even at their default
            if f.oneof is None:
                if f.kind in _VARINT_KINDS and int(v) == 0:
                    continue
                if f.kind in ("float", "double") and float(v) == 0.0:
                    continue
                if f.kind in ("string", "bytes") and len(v) == 0:
                    continue
            _encode_scalar(f, v, out)
    return b"".join(out)


def _encode_map_entry(kf: Field, vf: Field, mk, mv, entry: List[bytes]) -> None:
    if isinstance(mk, str):
        if mk != "":
            _encode_scalar(kf, mk, entry)
    elif int(mk) != 0:
        _encode_scalar(kf, mk, entry)
    if vf.kind == "message":
        payload = encode_message(vf.msg, mv)
        _encode_tag(vf.num, 2, entry)
        encode_varint(len(payload), entry)
        entry.append(payload)
    else:
        if isinstance(mv, str):
            if mv != "":
                _encode_scalar(vf, mv, entry)
        elif isinstance(mv, (bytes, bytearray)):
            if len(mv):
                _encode_scalar(vf, mv, entry)
        elif isinstance(mv, float):
            if mv != 0.0:
                _encode_scalar(vf, mv, entry)
        elif int(mv) != 0:
            _encode_scalar(vf, mv, entry)


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------


def _skip_field(buf, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = decode_varint(buf, pos)
    elif wire_type == 1:
        pos += 8
    elif wire_type == 2:
        length, pos = decode_varint(buf, pos)
        pos += length
    elif wire_type == 5:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    if pos > len(buf):
        raise ValueError("truncated message")
    return pos


def _decode_scalar(f: Field, buf, pos: int, wire_type: int) -> Tuple[Any, int]:
    kind = f.kind
    if wire_type == 0:
        raw, pos = decode_varint(buf, pos)
        if kind in ("int32", "int64"):
            return _signed(raw), pos
        if kind == "bool":
            return bool(raw), pos
        return raw, pos
    if wire_type == 1:
        if pos + 8 > len(buf):
            raise ValueError("truncated fixed64 field")
        val = struct.unpack_from("<d", buf, pos)[0]
        return val, pos + 8
    if wire_type == 5:
        if pos + 4 > len(buf):
            raise ValueError("truncated fixed32 field")
        val = struct.unpack_from("<f", buf, pos)[0]
        return val, pos + 4
    if wire_type == 2:
        length, pos = decode_varint(buf, pos)
        if pos + length > len(buf):
            raise ValueError("truncated length-delimited field")
        raw = bytes(buf[pos : pos + length])
        pos += length
        if kind == "string":
            return raw.decode("utf-8"), pos
        return raw, pos
    raise ValueError(f"unsupported wire type {wire_type} for {kind}")


def decode_message(spec: MessageSpec, buf) -> Dict[str, Any]:
    """Decode ``buf`` into a plain dict according to ``spec``.

    Repeated fields decode to lists, maps to dicts, sub-messages to dicts.
    Absent proto3 scalars keep their implicit defaults *out* of the dict.
    """
    if isinstance(buf, (bytes, bytearray)):
        buf = memoryview(buf)
    result: Dict[str, Any] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = decode_varint(buf, pos)
        num, wire_type = tag >> 3, tag & 0x7
        f = spec.by_num.get(num)
        if f is None:
            pos = _skip_field(buf, pos, wire_type)
            continue
        if f.kind == "map":
            length, pos = decode_varint(buf, pos)
            if pos + length > n:
                raise ValueError("truncated map entry")
            entry = buf[pos : pos + length]
            pos += length
            k, v = _decode_map_entry(f, entry)
            result.setdefault(f.name, {})[k] = v
        elif f.kind == "message":
            length, pos = decode_varint(buf, pos)
            if pos + length > n:
                raise ValueError("truncated sub-message")
            sub = decode_message(f.msg, buf[pos : pos + length])
            pos += length
            if f.repeated:
                result.setdefault(f.name, []).append(sub)
            else:
                result[f.name] = sub
        elif f.repeated:
            if wire_type == 2 and f.kind in _VARINT_KINDS | {"float", "double"}:
                # packed
                length, pos = decode_varint(buf, pos)
                end = pos + length
                if end > n:
                    raise ValueError("truncated packed field")
                vals = result.setdefault(f.name, [])
                while pos < end:
                    if f.kind == "double":
                        if pos + 8 > end:
                            raise ValueError("truncated packed field")
                        vals.append(struct.unpack_from("<d", buf, pos)[0])
                        pos += 8
                    elif f.kind == "float":
                        if pos + 4 > end:
                            raise ValueError("truncated packed field")
                        vals.append(struct.unpack_from("<f", buf, pos)[0])
                        pos += 4
                    else:
                        raw, pos = decode_varint(buf, pos)
                        if f.kind in ("int32", "int64"):
                            raw = _signed(raw)
                        elif f.kind == "bool":
                            raw = bool(raw)
                        vals.append(raw)
            else:
                val, pos = _decode_scalar(f, buf, pos, wire_type)
                result.setdefault(f.name, []).append(val)
        else:
            val, pos = _decode_scalar(f, buf, pos, wire_type)
            result[f.name] = val
    return result


def _decode_map_entry(f: Field, entry) -> Tuple[Any, Any]:
    kf, vf = f.map_kv
    key: Any = "" if kf.kind == "string" else 0
    value: Any = None
    pos = 0
    n = len(entry)
    while pos < n:
        tag, pos = decode_varint(entry, pos)
        num, wire_type = tag >> 3, tag & 0x7
        if num == 1:
            key, pos = _decode_scalar(kf, entry, pos, wire_type)
        elif num == 2:
            if vf.kind == "message":
                length, pos = decode_varint(entry, pos)
                if pos + length > n:
                    raise ValueError("truncated map value")
                value = decode_message(vf.msg, entry[pos : pos + length])
                pos += length
            else:
                value, pos = _decode_scalar(vf, entry, pos, wire_type)
        else:
            pos = _skip_field(entry, pos, wire_type)
    if value is None:
        if vf.kind == "message":
            value = {}
        elif vf.kind == "string":
            value = ""
        elif vf.kind == "bytes":
            value = b""
        elif vf.kind in ("float", "double"):
            value = 0.0
        elif vf.kind == "bool":
            value = False
        else:
            value = 0
    return key, value
