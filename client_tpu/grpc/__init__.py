"""KServe v2 GRPC client namespace (mirrors ``tritonclient.grpc``)."""

from .._base import (
    BasicAuth,
    InferenceServerClientBase,
    InferenceServerClientPlugin,
    Request,
)
from .._tensor import InferInput, InferRequestedOutput
from ..utils import InferenceServerException
from ._client import CallContext, InferenceServerClient, KeepAliveOptions
from ._infer import InferResult

__all__ = [
    "BasicAuth",
    "CallContext",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferenceServerClient",
    "InferenceServerClientBase",
    "InferenceServerClientPlugin",
    "InferenceServerException",
    "KeepAliveOptions",
    "Request",
]
