"""KServe v2 GRPC client namespace (mirrors ``tritonclient.grpc``)."""

from .._base import (
    BasicAuth,
    InferenceServerClientBase,
    InferenceServerClientPlugin,
    Request,
)
from .._tensor import InferInput, InferRequestedOutput
from ..utils import InferenceServerException
from ._client import CallContext, InferenceServerClient, KeepAliveOptions
from ._infer import InferResult


def proto_path() -> str:
    """Filesystem path of the vendored ``grpc_service.proto``.

    Ships as package data so a pip install can generate stubs in any
    language: ``protoc -I $(dirname path) --go_out=... grpc_service.proto``
    (reference analog: the vendored proto tree the generated-stub examples
    build against). Generated from the wire specs by ``tools/gen_proto.py``
    and drift-gated in CI."""
    import os

    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "grpc_service.proto")


__all__ = [
    "proto_path",
    "BasicAuth",
    "CallContext",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferenceServerClient",
    "InferenceServerClientBase",
    "InferenceServerClientPlugin",
    "InferenceServerException",
    "KeepAliveOptions",
    "Request",
]
