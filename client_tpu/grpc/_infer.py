"""GRPC request building and result decoding (dict-form messages).

The GRPC analogue of ``http/_utils.py`` + ``http/_infer_result.py``: builds
``ModelInferRequest`` dicts from the shared value model (binary tensors ride
``raw_input_contents`` as zero-copy chunks; JSON-mode data uses the typed
``InferTensorContents`` fields) and decodes ``ModelInferResponse`` dicts.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .._tensor import ArenaOutputsMixin, InferInput, InferRequestedOutput
from ..utils import (
    RESERVED_REQUEST_PARAMETERS,
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)

def to_grpc_compression(algorithm: Optional[str]):
    """Map a ``compression_algorithm`` string to a ``grpc.Compression`` value.

    Parity with reference ``grpc/_utils.py:142-153`` (``_grpc_compression_type``)
    with one deliberate deviation: ``None`` maps to ``None`` (inherit the
    channel's default compression) instead of ``NoCompression``, so a
    channel constructed with ``grpc.default_compression_algorithm`` keeps
    working when no per-call algorithm is given. ``"deflate"``/``"gzip"`` →
    the grpc enum; any other value warns and falls back to no compression.
    """
    import grpc

    if algorithm is None:
        return None
    if isinstance(algorithm, str):
        lowered = algorithm.lower()
        if lowered == "deflate":
            return grpc.Compression.Deflate
        if lowered == "gzip":
            return grpc.Compression.Gzip
    import warnings

    warnings.warn(
        f"unsupported client-side compression algorithm {algorithm!r}; "
        "using no compression",
        stacklevel=3,
    )
    return grpc.Compression.NoCompression


# typed-contents field per Triton datatype (InferTensorContents)
_CONTENTS_FIELD = {
    "BOOL": "bool_contents",
    "INT8": "int_contents",
    "INT16": "int_contents",
    "INT32": "int_contents",
    "INT64": "int64_contents",
    "UINT8": "uint_contents",
    "UINT16": "uint_contents",
    "UINT32": "uint_contents",
    "UINT64": "uint64_contents",
    "FP32": "fp32_contents",
    "FP64": "fp64_contents",
    "BYTES": "bytes_contents",
}


def to_infer_parameter(value: Any) -> Dict[str, Any]:
    if isinstance(value, bool):
        return {"bool_param": value}
    if isinstance(value, int):
        return {"int64_param": value}
    if isinstance(value, float):
        return {"double_param": value}
    if isinstance(value, str):
        return {"string_param": value}
    raise InferenceServerException(
        f"unsupported parameter type {type(value).__name__} (bool/int/float/str)"
    )


def from_infer_parameter(param: Dict[str, Any]) -> Any:
    for key in (
        "bool_param",
        "int64_param",
        "string_param",
        "double_param",
        "uint64_param",
        "uint32_param",  # LogSettings oneof
    ):
        if key in param:
            return param[key]
    return None


def build_infer_request(
    model_name: str,
    inputs: Sequence[InferInput],
    model_version: str = "",
    outputs: Optional[Sequence[InferRequestedOutput]] = None,
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Build a ModelInferRequest dict for the wire codec."""
    request: Dict[str, Any] = {"model_name": model_name}
    if model_version:
        request["model_version"] = model_version
    if request_id:
        request["id"] = request_id

    params: Dict[str, Any] = {}
    if sequence_id:
        params["sequence_id"] = to_infer_parameter(int(sequence_id))
        params["sequence_start"] = to_infer_parameter(bool(sequence_start))
        params["sequence_end"] = to_infer_parameter(bool(sequence_end))
    if priority:
        params["priority"] = to_infer_parameter(int(priority))
    if timeout is not None:
        params["timeout"] = to_infer_parameter(int(timeout))
    if parameters:
        for key, value in parameters.items():
            if key in RESERVED_REQUEST_PARAMETERS:
                raise InferenceServerException(
                    f"parameter '{key}' is a reserved parameter and cannot be "
                    "specified as a custom parameter"
                )
            params[key] = to_infer_parameter(value)
    if params:
        request["parameters"] = params

    tensors = []
    raw_contents: List[bytes] = []
    any_raw = False
    for inp in inputs:
        tensor: Dict[str, Any] = {
            "name": inp.name(),
            "datatype": inp.datatype(),
            "shape": inp.shape(),
        }
        tparams = {}
        shm = inp._shared_memory_params()
        if shm is not None:
            region, byte_size, offset = shm
            tparams["shared_memory_region"] = to_infer_parameter(region)
            tparams["shared_memory_byte_size"] = to_infer_parameter(int(byte_size))
            if offset:
                tparams["shared_memory_offset"] = to_infer_parameter(int(offset))
        if tparams:
            tensor["parameters"] = tparams
        raw = inp._get_binary_data()
        if raw is not None:
            any_raw = True
            raw_contents.append(raw if isinstance(raw, bytes) else bytes(raw))
        elif shm is None and inp._json_data is not None:
            field = _CONTENTS_FIELD.get(inp.datatype())
            if field is None:
                raise InferenceServerException(
                    f"datatype {inp.datatype()} requires binary data on GRPC"
                )
            data = inp._json_data
            if field == "bytes_contents":
                data = [d.encode("utf-8") if isinstance(d, str) else bytes(d) for d in data]
            tensor["contents"] = {field: data}
        elif shm is None:
            raise InferenceServerException(f"input '{inp.name()}' has no data")
        tensors.append(tensor)
    if any_raw and any(t.get("contents") for t in tensors):
        raise InferenceServerException(
            "inputs must be uniform: cannot mix raw binary and typed contents "
            "in one request"
        )
    request["inputs"] = tensors
    if raw_contents:
        request["raw_input_contents"] = raw_contents

    if outputs:
        out_tensors = []
        for out in outputs:
            entry: Dict[str, Any] = {"name": out.name()}
            oparams = {}
            shm = out._shared_memory_params()
            if shm is not None:
                region, byte_size, offset = shm
                oparams["shared_memory_region"] = to_infer_parameter(region)
                oparams["shared_memory_byte_size"] = to_infer_parameter(int(byte_size))
                if offset:
                    oparams["shared_memory_offset"] = to_infer_parameter(int(offset))
            if out._class_count:
                oparams["classification"] = to_infer_parameter(int(out._class_count))
            if oparams:
                entry["parameters"] = oparams
            out_tensors.append(entry)
        request["outputs"] = out_tensors
    return request


class InferResult(ArenaOutputsMixin):
    """The result of an inference over GRPC (decoded ModelInferResponse)."""

    def __init__(self, response: Dict[str, Any]):
        self._response = response
        self._raw = response.get("raw_output_contents", [])

    @classmethod
    def from_response(cls, response: Dict[str, Any]) -> "InferResult":
        return cls(response)

    def get_response(self) -> Dict[str, Any]:
        return self._response

    def get_response_header(self, name: str, default: Optional[str] = None) -> Optional[str]:
        """A response metadata value (e.g. ORCA's ``endpoint-load-metrics``).

        Parity with the HTTP clients' header accessor: the unary infer
        paths stash the call's initial+trailing metadata here (GRPC
        metadata keys are lowercase on the wire; lookup is
        case-insensitive for drop-in symmetry with HTTP)."""
        headers = getattr(self, "_response_headers", None)
        if not headers:
            return default
        # wire metadata keys are already lowercase, so the common case
        # (every telemetry-enabled infer probes for the ORCA header) is a
        # single dict hit; the scan only runs for hand-stashed mixed case
        value = headers.get(name.lower())
        if value is not None:
            return value
        for key, value in headers.items():
            if key.lower() == name.lower():
                return value
        return default

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        for out in self._response.get("outputs", []):
            if out.get("name") == name:
                return out
        return None

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        # raw_output_contents aligns with non-shared-memory outputs in order
        outputs = self._response.get("outputs", [])
        raw_index = 0
        out = None
        for candidate in outputs:
            in_shm = "shared_memory_region" in candidate.get("parameters", {})
            if candidate.get("name") == name:
                out = candidate
                break
            if not in_shm:
                raw_index += 1
        if out is None:
            return None
        shape = out.get("shape", [])
        datatype = out.get("datatype", "")
        oparams = out.get("parameters", {})
        if "shared_memory_region" in oparams:
            lease = self._arena_lease_for(name)
            if lease is not None:
                # arena fast path: a zero-copy view over the leased slab,
                # pinned by the lease (reading after its last release
                # raises arena.ArenaLeaseReleased)
                return lease.as_numpy(datatype, shape)
            return None
        if raw_index < len(self._raw):
            raw = self._raw[raw_index]
            if datatype == "BYTES":
                return deserialize_bytes_tensor(raw).reshape(shape)
            if datatype == "BF16":
                return deserialize_bf16_tensor(raw).reshape(shape)
            return np.frombuffer(raw, dtype=triton_to_np_dtype(datatype)).reshape(shape)
        contents = out.get("contents")
        if contents:
            field = _CONTENTS_FIELD.get(datatype)
            data = contents.get(field, [])
            return np.array(data, dtype=triton_to_np_dtype(datatype)).reshape(shape)
        return None

    def as_jax(self, name: str, device=None):
        arr = self.as_numpy(name)
        if arr is None:
            return None
        if arr.dtype == np.object_:
            raise InferenceServerException("BYTES outputs cannot be placed on device")
        import jax

        return jax.device_put(arr, device)

    # decoupled-model helpers (reference: common.h IsFinalResponse/IsNullResponse)
    def is_final_response(self) -> bool:
        param = self._response.get("parameters", {}).get("triton_final_response", {})
        return bool(param.get("bool_param", False))

    def is_null_response(self) -> bool:
        return not self._response.get("outputs") and self.is_final_response()
