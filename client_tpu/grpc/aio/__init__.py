"""Asyncio KServe v2 GRPC client (mirrors ``tritonclient.grpc.aio``).

grpc.aio re-implementation over the same schema-driven wire codec
(reference: grpc/aio/__init__.py:50-810, ``stream_infer`` :688-798).
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence

import grpc
import grpc.aio

from ..._base import InferenceServerClientBase, Request
from ..._tensor import InferInput, InferRequestedOutput
from ...observe import TRACEPARENT_HEADER
from ...resilience import FATAL, AttemptBudget, classify_fault
from ...utils import InferenceServerException
from .. import _messages as M
from .._client import (
    INT32_MAX,
    KeepAliveOptions,
    _flatten_metadata,
    _to_exception,
)
from .._infer import (
    InferResult,
    build_infer_request,
    from_infer_parameter,
    to_grpc_compression,
)
from .._wire import decode_message, encode_message

__all__ = [
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferenceServerClient",
    "KeepAliveOptions",
]


class InferenceServerClient(InferenceServerClientBase):
    """Asyncio client for the KServe v2 GRPC protocol."""

    _FRONTEND = "grpc_aio"
    _BATCH_AIO = True

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional["grpc.ChannelCredentials"] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[List] = None,
    ):
        super().__init__()
        self._url = url
        self._verbose = verbose
        if channel_args is not None:
            options = list(channel_args)
        else:
            ka = keepalive_options or KeepAliveOptions()
            options = [
                ("grpc.max_send_message_length", INT32_MAX),
                ("grpc.max_receive_message_length", INT32_MAX),
                ("grpc.keepalive_time_ms", ka.keepalive_time_ms),
                ("grpc.keepalive_timeout_ms", ka.keepalive_timeout_ms),
                ("grpc.keepalive_permit_without_calls", int(ka.keepalive_permit_without_calls)),
                ("grpc.http2.max_pings_without_data", ka.http2_max_pings_without_data),
            ]
        if creds is not None:
            self._channel = grpc.aio.secure_channel(url, creds, options=options)
        elif ssl:
            rc = open(root_certificates, "rb").read() if root_certificates else None
            pk = open(private_key, "rb").read() if private_key else None
            cc = open(certificate_chain, "rb").read() if certificate_chain else None
            self._channel = grpc.aio.secure_channel(
                url, grpc.ssl_channel_credentials(rc, pk, cc), options=options
            )
        else:
            self._channel = grpc.aio.insecure_channel(url, options=options)
        self._callables: Dict[str, Any] = {}

    async def close(self) -> None:
        await self._channel.close()

    async def __aenter__(self) -> "InferenceServerClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- transport ---------------------------------------------------------
    def _callable(self, method: str, streaming: bool = False):
        cached = self._callables.get(method)
        if cached is not None:
            return cached
        req_spec, resp_spec = M.METHODS[method]
        path = M.method_path(method)
        serializer = lambda d: encode_message(req_spec, d)  # noqa: E731
        deserializer = lambda b: decode_message(resp_spec, b)  # noqa: E731
        if streaming:
            c = self._channel.stream_stream(
                path, request_serializer=serializer, response_deserializer=deserializer
            )
        else:
            c = self._channel.unary_unary(
                path, request_serializer=serializer, response_deserializer=deserializer
            )
        self._callables[method] = c
        return c

    def _metadata(self, headers: Optional[Dict[str, str]]):
        hdrs = dict(headers or {})
        request = Request(hdrs)
        self._call_plugin(request)
        return tuple(request.headers.items()) or None

    async def _call(
        self, method, request, headers=None, client_timeout=None,
        compression_algorithm=None, idempotent=True, resilience=None,
        span=None, metadata_sink=None,
    ):
        """``metadata_sink``: when given, the response's initial+trailing
        metadata (string values only) land in the dict — the GRPC twin of
        HTTP response headers (e.g. ORCA's ``endpoint-load-metrics``)."""
        policy = self._resilience_for(resilience)
        budget = AttemptBudget(policy, client_timeout)

        async def attempt():
            attempt_timeout = budget.attempt_timeout_s(
                status="StatusCode.DEADLINE_EXCEEDED")
            try:
                call = self._callable(method)(
                    request,
                    metadata=self._metadata(headers),
                    timeout=attempt_timeout,
                    compression=to_grpc_compression(compression_algorithm),
                )
                response = await call
                if metadata_sink is not None:
                    metadata_sink.clear()  # a retried attempt must not mix
                    metadata_sink.update(_flatten_metadata(
                        await call.initial_metadata(),
                        await call.trailing_metadata()))
                return response
            except grpc.aio.AioRpcError as e:
                raise _to_exception(e) from e

        run_attempt = attempt
        on_retry = None
        if span is not None:
            async def run_attempt():
                t_a = time.perf_counter_ns()
                try:
                    result = await attempt()
                except BaseException:
                    span.phase("attempt", t_a, time.perf_counter_ns())
                    raise
                end = time.perf_counter_ns()
                span.phase("attempt", t_a, end)
                # unary call: the SUCCESSFUL attempt is the ttfb window (a
                # retried request must not fold failed attempts + backoff
                # into it)
                span.phase("ttfb", t_a, end)
                return result

            def on_retry(n, exc, delay):
                span.event("retry", attempt=n, backoff_s=round(delay, 6),
                           error=type(exc).__name__)

        if policy is None:
            return await run_attempt()
        return await policy.execute_async(
            run_attempt, idempotent=idempotent, timeout_s=client_timeout,
            on_retry=on_retry)

    # -- surface (async twins of the sync client) ---------------------------
    async def _health(self, method, field, headers, client_timeout,
                      probe: bool) -> bool:
        """Async twin of the sync client's ``_health``: transport failures
        raise by default; ``probe=True`` maps connect/transient/timeout-class
        failures to False and bypasses the resilience policy."""
        try:
            resp = await self._call(method, {}, headers, client_timeout,
                                    resilience=False if probe else None)
        except InferenceServerException as e:
            if probe and classify_fault(e) != FATAL:
                return False
            raise
        return bool(resp.get(field, False))

    async def is_server_live(self, headers=None, client_timeout=None,
                             probe: bool = False) -> bool:
        return await self._health(
            "ServerLive", "live", headers, client_timeout, probe)

    async def is_server_ready(self, headers=None, client_timeout=None,
                              probe: bool = False) -> bool:
        return await self._health(
            "ServerReady", "ready", headers, client_timeout, probe)

    async def is_model_ready(self, model_name, model_version="", headers=None, client_timeout=None) -> bool:
        resp = await self._call(
            "ModelReady", {"name": model_name, "version": model_version}, headers, client_timeout
        )
        return bool(resp.get("ready", False))

    async def get_server_metadata(self, headers=None, client_timeout=None):
        return await self._call("ServerMetadata", {}, headers, client_timeout)

    async def get_model_metadata(self, model_name, model_version="", headers=None, client_timeout=None):
        metadata = await self._call(
            "ModelMetadata", {"name": model_name, "version": model_version}, headers, client_timeout
        )
        # captured into the integrity contract cache: later responses
        # are validated against this fetched truth (never vice versa)
        self._integrity_note_metadata(model_name, metadata)
        return metadata

    async def get_model_config(self, model_name, model_version="", headers=None, client_timeout=None):
        return await self._call(
            "ModelConfig", {"name": model_name, "version": model_version}, headers, client_timeout
        )

    async def get_model_repository_index(self, headers=None, client_timeout=None):
        return (await self._call("RepositoryIndex", {}, headers, client_timeout)).get("models", [])

    async def load_model(self, model_name, headers=None, config=None, files=None, client_timeout=None):
        params: Dict[str, Any] = {}
        if config is not None:
            params["config"] = {"string_param": config}
        for p, content in (files or {}).items():
            params[p] = {"bytes_param": content}
        req: Dict[str, Any] = {"model_name": model_name}
        if params:
            req["parameters"] = params
        await self._call("RepositoryModelLoad", req, headers, client_timeout)

    async def unload_model(self, model_name, headers=None, unload_dependents=False, client_timeout=None):
        await self._call(
            "RepositoryModelUnload",
            {"model_name": model_name,
             "parameters": {"unload_dependents": {"bool_param": unload_dependents}}},
            headers, client_timeout,
        )

    async def get_inference_statistics(self, model_name="", model_version="", headers=None, client_timeout=None):
        return await self._call(
            "ModelStatistics", {"name": model_name, "version": model_version}, headers, client_timeout
        )

    async def get_system_shared_memory_status(self, region_name="", headers=None, client_timeout=None):
        resp = await self._call("SystemSharedMemoryStatus", {"name": region_name}, headers, client_timeout)
        return list(resp.get("regions", {}).values())

    async def register_system_shared_memory(self, name, key, byte_size, offset=0, headers=None, client_timeout=None):
        await self._shm_call_async(
            "system", "register", self._call,
            "SystemSharedMemoryRegister",
            {"name": name, "key": key, "offset": offset, "byte_size": byte_size},
            headers, client_timeout,
        )

    async def unregister_system_shared_memory(self, name="", headers=None, client_timeout=None):
        await self._shm_call_async(
            "system", "unregister", self._call,
            "SystemSharedMemoryUnregister", {"name": name}, headers,
            client_timeout, region_name=name)

    async def _register_handle(self, method, name, raw_handle, device_id, byte_size, headers, client_timeout):
        if isinstance(raw_handle, str):
            raw_handle = raw_handle.encode("ascii")
        await self._shm_call_async(
            "cuda" if method.startswith("Cuda") else "tpu", "register",
            self._call,
            method,
            {"name": name, "raw_handle": raw_handle, "device_id": device_id, "byte_size": byte_size},
            headers, client_timeout,
        )

    async def get_cuda_shared_memory_status(self, region_name="", headers=None, client_timeout=None):
        resp = await self._call("CudaSharedMemoryStatus", {"name": region_name}, headers, client_timeout)
        return list(resp.get("regions", {}).values())

    async def register_cuda_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None):
        await self._register_handle("CudaSharedMemoryRegister", name, raw_handle, device_id, byte_size, headers, client_timeout)

    async def unregister_cuda_shared_memory(self, name="", headers=None, client_timeout=None):
        await self._shm_call_async(
            "cuda", "unregister", self._call,
            "CudaSharedMemoryUnregister", {"name": name}, headers,
            client_timeout)

    async def get_tpu_shared_memory_status(self, region_name="", headers=None, client_timeout=None):
        resp = await self._call("TpuSharedMemoryStatus", {"name": region_name}, headers, client_timeout)
        return list(resp.get("regions", {}).values())

    async def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None):
        await self._register_handle("TpuSharedMemoryRegister", name, raw_handle, device_id, byte_size, headers, client_timeout)

    async def unregister_tpu_shared_memory(self, name="", headers=None, client_timeout=None):
        await self._shm_call_async(
            "tpu", "unregister", self._call,
            "TpuSharedMemoryUnregister", {"name": name}, headers,
            client_timeout, region_name=name)

    async def update_log_settings(self, settings, headers=None, client_timeout=None):
        req: Dict[str, Any] = {"settings": {}}
        for key, value in settings.items():
            if isinstance(value, bool):
                req["settings"][key] = {"bool_param": value}
            elif isinstance(value, int):
                req["settings"][key] = {"uint32_param": value}
            else:
                req["settings"][key] = {"string_param": str(value)}
        resp = await self._call("LogSettings", req, headers, client_timeout)
        return {k: from_infer_parameter(v) for k, v in resp.get("settings", {}).items()}

    async def get_log_settings(self, headers=None, client_timeout=None):
        resp = await self._call("LogSettings", {}, headers, client_timeout)
        return {k: from_infer_parameter(v) for k, v in resp.get("settings", {}).items()}

    async def update_trace_settings(self, model_name=None, settings=None, headers=None, client_timeout=None):
        req: Dict[str, Any] = {"settings": {}}
        if model_name:
            req["model_name"] = model_name
        for key, value in (settings or {}).items():
            if value is None:
                req["settings"][key] = {}
            elif isinstance(value, (list, tuple)):
                req["settings"][key] = {"value": [str(v) for v in value]}
            else:
                req["settings"][key] = {"value": [str(value)]}
        resp = await self._call("TraceSetting", req, headers, client_timeout)
        return {k: v.get("value", []) for k, v in resp.get("settings", {}).items()}

    async def get_trace_settings(self, model_name=None, headers=None, client_timeout=None):
        req = {"model_name": model_name} if model_name else {}
        resp = await self._call("TraceSetting", req, headers, client_timeout)
        return {k: v.get("value", []) for k, v in resp.get("settings", {}).items()}

    # -- inference ---------------------------------------------------------
    async def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        parameters: Optional[Dict[str, Any]] = None,
        compression_algorithm: Optional[str] = None,
        resilience=None,
        tenant: Optional[str] = None,
    ) -> InferResult:
        span = self._obs_begin(self._FRONTEND, model_name)
        if span is not None and tenant is not None:
            # client-side QoS attribution only (see client_tpu.tenancy);
            # the tenant is never sent on the wire
            span.event("tenant", tenant=tenant)
        actx = None
        try:
            # arena data plane: promote staged binary inputs into leased
            # slabs and ensure (cached) region registrations BEFORE the
            # request is built, so it rides shm params
            actx = await self._arena_bind_async(inputs, outputs)
            request = build_infer_request(
                model_name, inputs, model_version, outputs, request_id,
                sequence_id, sequence_start, sequence_end, priority, timeout, parameters,
            )
            # unconditional like HTTP: ORCA opt-in must not depend on
            # whether this request got a span
            hdrs = self._orca_opt_in(dict(headers or {}))
            if span is not None:
                hdrs[TRACEPARENT_HEADER] = span.traceparent()
                span.phase("serialize", span.start_ns, time.perf_counter_ns())
            metadata_sink: Dict[str, str] = {}
            response = await self._call(
                "ModelInfer", request, hdrs, client_timeout, compression_algorithm,
                idempotent=sequence_id == 0, resilience=resilience, span=span,
                metadata_sink=metadata_sink,
            )
            if span is not None:
                t_deser = time.perf_counter_ns()
            result = InferResult(response)
            result._response_headers = metadata_sink
            if actx is not None:
                actx.finish(result)
            # contract validation: the result never reaches the caller
            # (nor the ORCA path below) un-checked
            self._integrity_check(result, inputs, outputs, request_id,
                                  model_name)
        except BaseException as e:
            if span is not None:
                self._telemetry.finish(span, error=e)
            raise
        finally:
            if actx is not None:
                actx.settle()
        if span is not None:
            span.phase("deserialize", t_deser, time.perf_counter_ns())
            self._telemetry.finish(span)
        # after the phase capture: ORCA bookkeeping (header parse + gauge
        # writes) must not masquerade as deserialize milliseconds
        self._orca_ingest(result)
        return result

    async def stream_infer(
        self,
        inputs_iterator: AsyncIterator[Dict[str, Any]],
        stream_timeout: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        compression_algorithm: Optional[str] = None,
    ) -> AsyncIterator:
        """Bi-di streaming: consume request dicts, yield (result, error) pairs.

        Each item from ``inputs_iterator`` is a kwargs dict for
        ``build_infer_request`` (model_name, inputs, sequence_id, ...).
        The returned async iterator has a ``cancel()`` via the underlying
        call (raises asyncio.CancelledError in the consumer).

        With telemetry configured the stream is traced as a
        ``StreamSpan`` (open -> first-response TTFT -> per-response marks
        -> EOF/cancel/error) and a stream-level ``traceparent`` metadata
        key joins every request on the call to the server's access
        records.
        """
        span = self._obs_begin_stream(self._FRONTEND, "", op="stream")
        self._last_stream_span = span
        if span is not None:
            headers = dict(headers or {})
            headers[TRACEPARENT_HEADER] = span.traceparent()

        async def request_gen():
            async for kwargs in inputs_iterator:
                enable_final = kwargs.pop("enable_empty_final_response", False)
                # ensure-only arena binding per stream request (no
                # promotion: the stream outlives each yielded request)
                await self._arena_bind_async(
                    kwargs.get("inputs") or (), kwargs.get("outputs"),
                    promote=False)
                req = build_infer_request(**kwargs)
                if enable_final:
                    req.setdefault("parameters", {})[
                        "triton_enable_empty_final_response"
                    ] = {"bool_param": True}
                yield req

        call = self._callable("ModelStreamInfer", streaming=True)(
            request_gen(),
            metadata=self._metadata(headers),
            timeout=stream_timeout,
            compression=to_grpc_compression(compression_algorithm),
        )

        class _ResponseIterator:
            """Async iterator of (result, error) pairs with ``cancel()``."""

            def __init__(self, rpc_call, stream_span, telemetry):
                self._call = rpc_call
                self._span = stream_span
                self._telemetry = telemetry

            def _finish(self, error=None, abandoned=False):
                if self._span is not None and self._telemetry is not None:
                    self._telemetry.finish_stream(
                        self._span, error=error, abandoned=abandoned)

            def cancel(self) -> bool:
                self._finish(abandoned=True)
                return self._call.cancel()

            def __aiter__(self):
                return self

            async def __anext__(self):
                try:
                    response = await self._call.read()
                except grpc.aio.AioRpcError as e:
                    if e.code() == grpc.StatusCode.CANCELLED:
                        self._finish(abandoned=True)
                        raise StopAsyncIteration
                    err = _to_exception(e)
                    self._finish(error=err)
                    raise err from e
                if response is grpc.aio.EOF:
                    self._finish()
                    raise StopAsyncIteration
                err = response.get("error_message")
                if err:
                    if self._span is not None:
                        self._span.event(
                            "stream_error", error="InferenceServerException")
                    return None, InferenceServerException(err)
                if self._span is not None:
                    self._span.mark()
                return InferResult(response.get("infer_response", {})), None

        return _ResponseIterator(call, span, self._telemetry)

    def stream_span(self):
        """The most recent ``stream_infer``'s StreamSpan (None without
        telemetry)."""
        return getattr(self, "_last_stream_span", None)
