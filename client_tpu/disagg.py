"""Disaggregated prefill/decode serving: role-aware routing, verified KV
handoff, and re-prefill recovery when a decode replica dies mid-stream.

Production LLM fleets split compute-bound prefill from memory-bound decode
onto differently-provisioned replica classes (Hermes, arXiv:2409.04249).
:class:`DisaggClient` / :class:`AioDisaggClient` run that split as a
client-side protocol over the existing pool machinery:

1. **Prefill leg** — routed to a ``role="prefill"`` endpoint
   (``EndpointSpec`` labels, ``pool.select(role=...)``) and executed as
   ONE pinned unary infer whose ``KV`` output lands directly in a
   shared-memory arena slab (``ShmArena.request_output``). Steady state
   does zero region creates and zero registration RPCs: the arena's
   per-``(endpoint, region)`` registration cache covers both legs after
   first use.
2. **Verified handoff** — the exported cache is summarized by a
   :class:`KvHandoff` manifest (region/offset/byte span, dtype, shape,
   fill position, first pending token) plus a blake2b digest over the
   slab bytes. The digest and manifest are re-verified immediately
   before the decode stream opens; any mismatch raises a typed
   :class:`HandoffCorrupt` — a corrupted handoff can never become
   silently-garbage tokens.
3. **Decode leg** — a ``role="decode"`` endpoint streams tokens from the
   handed-off cache (``decoder_lm_kv_decode``) through a replica-pinned
   SSE generate stream. The KV rides the generate request as a
   shared-memory *reference* (region/offset), not JSON payload.
4. **Re-prefill recovery** — a decode replica dying mid-stream is not
   the end of the session: prefill is a pure function of the token
   sequence (idempotent by construction), so the client re-runs it over
   ``prompt + already-emitted tokens`` on a healthy prefill replica,
   verifies the fresh handoff, and resumes decode on a surviving decode
   replica with ``START_INDEX`` pinned past the emitted prefix. All legs
   draw from ONE shared :class:`~client_tpu.resilience.AttemptBudget`;
   the caller's stream never repeats or drops a token (an index replay
   is deduplicated and content-checked, a gap is typed). When recovery
   is impossible — budget spent, attempts exhausted, no surviving
   decode replica — a typed :class:`DecodeAbandoned` names the lost
   replica and how many tokens were already delivered.
5. **Typed role fallback** — a role with no usable endpoint at session
   start (absent, fully unavailable, or saturated) degrades to
   monolithic single-replica serving (``tiny_lm_generate`` routed
   role-less), emitting a :class:`~client_tpu.pool.RoleFallback` pool
   event first. Degradation is observable, never silent.

Admission charges the two legs to SEPARATE lanes (``disagg:prefill`` /
``disagg:decode``) so a decode-heavy fleet cannot starve prefill
admission or vice versa. Every step is flight-recorded under the
``disagg`` layer (``route``, ``handoff``, ``register_check``,
``verify``, ``dedup``, ``decode_died``, ``reprefill``, ``fallback``).

Both model halves share the zoo decoder's weights and compiled step, so
the disaggregated token stream is bit-exact against monolithic
``tiny_lm_generate`` output — asserted by ``tests/test_disagg.py`` and
re-proven live by ``tools/capacity_gate.py --disagg``.

Usage::

    from client_tpu.pool import EndpointSpec, PoolClient
    from client_tpu.disagg import DisaggClient

    pool = PoolClient(
        [EndpointSpec("10.0.0.1:8000", role="prefill"),
         EndpointSpec("10.0.0.2:8000", role="decode"),
         EndpointSpec("10.0.0.3:8000", role="decode")],
        protocol="http", shm_arena=True)
    client = DisaggClient(pool)
    for event in client.generate_stream([3, 1, 4, 1, 5], max_tokens=32):
        print(event["INDEX"], event["NEXT_TOKEN"])

``docs/disaggregation.md`` has the full interaction matrix.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import flight as _flight
from .arena import LeaseDigest
from ._tensor import InferInput, InferRequestedOutput
from .admission import AdmissionRejected
from .pool import (
    _PoolClientBase,
    AioPoolClient,
    EndpointSpec,
    NoEndpointAvailableError,
    PoolClient,
    RoleFallback,
)
from .resilience import (
    AttemptBudget,
    CONNECT,
    TIMEOUT,
    TRANSIENT,
    classify_fault,
)
from .utils import InferenceServerException, triton_to_np_dtype

__all__ = [
    "AioDisaggClient",
    "DecodeAbandoned",
    "DisaggClient",
    "DisaggConfigError",
    "DisaggError",
    "HandoffCorrupt",
    "KvHandoff",
    "PREFILL_ROLE",
    "DECODE_ROLE",
]

PREFILL_ROLE = "prefill"
DECODE_ROLE = "decode"

# WFQ lane labels the two legs are charged to (lazily created on the
# pool's admission controller; both at the default lane's rank so disagg
# traffic is peer to — not above — ordinary requests)
PREFILL_LANE: Tuple[str, int] = ("disagg:prefill", 1)
DECODE_LANE: Tuple[str, int] = ("disagg:decode", 1)

# blake2b-128 (collision-safe for corruption detection): the hashing
# itself now lives in arena.LeaseDigest, shared with the integrity
# layer's opt-in output-slab seals
_DIGEST_SIZE = LeaseDigest.DIGEST_SIZE


class DisaggError(InferenceServerException):
    """Base for every typed disaggregation error."""

    def __init__(self, msg: str, status: str = "DISAGG"):
        super().__init__(msg, status=status)


class DisaggConfigError(DisaggError):
    """Disaggregated serving was composed with something it rejects by
    design: a non-pool substrate, a sync/aio mismatch, a pool without
    the shm arena, or a KV contract the arena cannot stage."""

    def __init__(self, msg: str):
        super().__init__(msg, status="DISAGG_CONFIG")


class HandoffCorrupt(DisaggError):
    """The KV handoff failed verification between prefill and decode —
    digest mismatch, manifest disagreement, or a resumed stream replaying
    an index with DIFFERENT content. The session refuses to decode from
    (or emit) corrupt state; it never streams garbage tokens.

    ``field`` names what disagreed (``digest``, ``pos``, ``dtype``,
    ``shape``, ``token``); ``expected``/``actual`` carry both sides."""

    def __init__(self, url: str, field: str, expected: Any, actual: Any):
        super().__init__(
            f"KV handoff verification failed at {url or '<client>'}: "
            f"{field} expected {expected!r}, got {actual!r}",
            status="DISAGG_HANDOFF_CORRUPT")
        self.url = url
        self.field = field
        self.expected = expected
        self.actual = actual


class DecodeAbandoned(DisaggError):
    """A decode replica died mid-stream and recovery is impossible
    (attempt budget spent, failover attempts exhausted, or no healthy
    replica to re-prefill/resume on). ``url`` names the lost replica,
    ``emitted`` how many tokens the caller already received (all
    delivered exactly once), ``cause`` the terminal failure."""

    def __init__(self, url: str, emitted: int, cause: BaseException):
        super().__init__(
            f"decode replica {url} lost mid-stream after {emitted} "
            f"token(s); recovery failed: {type(cause).__name__}: {cause}",
            status="DISAGG_DECODE_ABANDONED")
        self.url = url
        self.emitted = emitted
        self.cause = cause


class KvHandoff:
    """The verified-handoff manifest: where the exported KV lives in the
    arena, what tensor it claims to be, and the blake2b digest of its
    bytes at export time. ``verify()`` recomputes the digest from the
    live slab immediately before decode — the window where a stray write
    (or a buggy re-home) could corrupt the cache."""

    __slots__ = ("region", "offset", "nbytes", "datatype", "shape",
                 "digest", "pos", "next_token", "prefill_url", "_out")

    def __init__(self, out, region: str, offset: int, nbytes: int,
                 datatype: str, shape: Sequence[int], digest: str,
                 pos: int, next_token: int, prefill_url: str):
        self._out = out  # the lease-bound InferRequestedOutput (owner)
        self.region = region
        self.offset = offset
        self.nbytes = nbytes
        self.datatype = datatype
        self.shape = list(shape)
        self.digest = digest
        self.pos = pos
        self.next_token = next_token
        self.prefill_url = prefill_url

    @property
    def lease(self):
        return getattr(self._out, "_arena_lease", None)

    def _slab_digest(self) -> str:
        lease = self.lease
        if lease is None:
            raise DisaggError("handoff lease already released",
                              status="DISAGG_HANDOFF_CORRUPT")
        return LeaseDigest(self.nbytes, self.digest).compute(lease)

    def verify(self, url: str = "") -> None:
        """Raise :class:`HandoffCorrupt` unless the live slab still hashes
        to the manifest digest."""
        actual = self._slab_digest()
        if actual != self.digest:
            raise HandoffCorrupt(url, "digest", self.digest, actual)

    def shm_reference(self) -> Dict[str, Any]:
        """The generate-extension object value referencing this handoff
        (resolved server-side exactly like infer's shm parameters)."""
        return {
            "shared_memory_region": self.region,
            "shared_memory_byte_size": self.nbytes,
            "shared_memory_offset": self.offset,
            "shape": list(self.shape),
        }

    def release(self) -> None:
        """Drop the arena lease (idempotent)."""
        out, self._out = self._out, None
        if out is not None:
            out.release_arena_lease()

    def __repr__(self) -> str:
        return (f"KvHandoff(region={self.region!r}, offset={self.offset}, "
                f"nbytes={self.nbytes}, pos={self.pos}, "
                f"digest={self.digest[:12]}..., from={self.prefill_url!r})")


class _DisaggBase:
    """Session orchestration shared by the sync and asyncio clients."""

    _AIO = False
    DEFAULT_MAX_TOKENS = 16

    def __init__(self, client: _PoolClientBase,
                 prefill_model: str = "decoder_lm_disagg_prefill",
                 decode_model: str = "decoder_lm_kv_decode",
                 fallback_model: str = "tiny_lm_generate",
                 prefill_role: str = PREFILL_ROLE,
                 decode_role: str = DECODE_ROLE):
        if not isinstance(client, _PoolClientBase):
            raise DisaggConfigError(
                f"DisaggClient needs a PoolClient/AioPoolClient substrate, "
                f"got {type(client).__name__}")
        if client._AIO != self._AIO:
            raise DisaggConfigError(
                "sync DisaggClient needs a PoolClient and AioDisaggClient "
                "an AioPoolClient (sync/aio mismatch)")
        if client.arena() is None:
            raise DisaggConfigError(
                "disaggregated serving hands the KV cache off through the "
                "shared-memory arena — build the pool with shm_arena=True")
        self.inner = client
        self.prefill_model = prefill_model
        self.decode_model = decode_model
        self.fallback_model = fallback_model
        self.prefill_role = prefill_role
        self.decode_role = decode_role
        self._kv_meta: Optional[Tuple[str, List[int]]] = None

    # -- delegation ----------------------------------------------------------
    @property
    def _FRONTEND(self) -> str:
        return "disagg+" + self.inner._FRONTEND

    def telemetry(self):
        return self.inner.telemetry()

    def arena(self):
        return self.inner.arena()

    def admission(self):
        return self.inner.admission()

    def endpoint_stats(self):
        return self.inner.endpoint_stats()

    def describe(self) -> Dict[str, Any]:
        return {
            "prefill_model": self.prefill_model,
            "decode_model": self.decode_model,
            "fallback_model": self.fallback_model,
            "prefill_role": self.prefill_role,
            "decode_role": self.decode_role,
            "roles": {str(k): v for k, v in self.inner.pool.roles().items()},
        }

    def __getattr__(self, name: str):
        if name.startswith("_") or name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    # -- shared helpers ------------------------------------------------------
    def _kv_contract(self, metadata: Dict[str, Any]) -> Tuple[str, List[int]]:
        """Resolve (and validate) the prefill model's KV output contract
        from its metadata: the arena slab is sized from it, so the shape
        must be fully static."""
        for out in metadata.get("outputs", []) or []:
            if out.get("name") == "KV":
                datatype = out.get("datatype")
                shape = [int(d) for d in out.get("shape", [])]
                if not shape or any(d < 0 for d in shape):
                    raise DisaggConfigError(
                        f"model '{self.prefill_model}' KV output shape "
                        f"{shape} is not static — the handoff slab cannot "
                        "be sized")
                if datatype == "BYTES":
                    raise DisaggConfigError(
                        "KV handoff needs a fixed-width datatype, "
                        "got BYTES")
                return datatype, shape
        raise DisaggConfigError(
            f"model '{self.prefill_model}' declares no 'KV' output — not "
            "a disaggregated prefill model")

    def _kv_nbytes(self, datatype: str, shape: Sequence[int]) -> int:
        item = np.dtype(triton_to_np_dtype(datatype)).itemsize
        return int(np.prod(shape)) * item

    @staticmethod
    def _normalize_prompt(tokens) -> List[int]:
        prompt = [int(t) for t in np.asarray(tokens).reshape(-1)]
        if not prompt:
            raise DisaggError("empty prompt")
        return prompt

    @staticmethod
    def _fallback_reason(cause: BaseException) -> str:
        return ("saturated" if isinstance(cause, AdmissionRejected)
                else "unavailable")

    def _is_role_outage(self, exc: BaseException) -> bool:
        """Does this selection failure mean the ROLE degraded (fallback),
        rather than a client-wide admission decision (propagate)?"""
        if isinstance(exc, NoEndpointAvailableError):
            return True
        return (isinstance(exc, AdmissionRejected)
                and exc.lane == "endpoint")

    def _build_handoff(self, result, kv_out, datatype: str,
                       shape: List[int], nbytes: int, n_tokens: int,
                       url: str) -> KvHandoff:
        """Digest + manifest over the slab the prefill just filled."""
        lease = kv_out._arena_lease
        digest = LeaseDigest.seal(lease, nbytes).hexdigest
        pos = int(np.asarray(result.as_numpy("POS")).reshape(-1)[0])
        next_token = int(
            np.asarray(result.as_numpy("NEXT_TOKEN")).reshape(-1)[0])
        if pos != n_tokens:
            # the server consumed a different number of tokens than the
            # client handed it: the cache does NOT represent this prompt
            kv_out.release_arena_lease()
            raise HandoffCorrupt(url, "pos", n_tokens, pos)
        handoff = KvHandoff(
            kv_out, lease.region_name, lease.offset, nbytes, datatype,
            shape, digest, pos, next_token, url)
        _flight.note(
            "disagg", "handoff", url=url, region=lease.region_name,
            offset=lease.offset, bytes=nbytes, digest=digest, pos=pos)
        return handoff

    def _decode_payload(self, handoff: KvHandoff, emitted: List[int],
                        max_tokens: int,
                        end_id: Optional[int]) -> Dict[str, Any]:
        payload: Dict[str, Any] = {
            "KV": handoff.shm_reference(),
            "POS": handoff.pos,
            "FIRST_TOKEN": handoff.next_token,
            "MAX_TOKENS": max_tokens - len(emitted),
            "START_INDEX": len(emitted),
        }
        if end_id is not None:
            payload["END_ID"] = int(end_id)
        return payload

    def _accept_event(self, event: Dict[str, Any], emitted: List[int],
                      url: str) -> Optional[Tuple[int, int]]:
        """Dedup/continuity gate for one decode stream event. Returns
        ``(token, index)`` to emit, or None when the event is a verified
        replay of an already-delivered token (skipped)."""
        token = int(event["NEXT_TOKEN"])
        index = int(event["INDEX"])
        if index < len(emitted):
            # a replayed index must carry the SAME token it did the first
            # time — same-content replays dedup silently, different
            # content is corruption, never a double emission
            if emitted[index] != token:
                raise HandoffCorrupt(url, "token", emitted[index], token)
            _flight.note("disagg", "dedup", url=url, index=index)
            return None
        if index > len(emitted):
            raise HandoffCorrupt(url, "index", len(emitted), index)
        emitted.append(token)
        return token, index

    @staticmethod
    def _finished(emitted: List[int], max_tokens: int,
                  end_id: Optional[int]) -> bool:
        if len(emitted) >= max_tokens:
            return True
        return bool(end_id is not None and emitted
                    and emitted[-1] == int(end_id))


class DisaggClient(_DisaggBase):
    """Synchronous disaggregated prefill/decode client over a
    :class:`~client_tpu.pool.PoolClient` (see the module docstring for
    the full protocol). Construct with a pool, or with a list of
    urls/``EndpointSpec`` to build (and own) one."""

    _AIO = False

    def __init__(self, client: Union[PoolClient, Sequence], *,
                 protocol: str = "http", **kwargs):
        pool_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                       if k not in ("prefill_model", "decode_model",
                                    "fallback_model", "prefill_role",
                                    "decode_role")}
        owns = False
        if not hasattr(client, "infer"):
            specs = [u if isinstance(u, EndpointSpec) else EndpointSpec(u)
                     for u in client]
            pool_kwargs.setdefault("shm_arena", True)
            client = PoolClient(specs, protocol=protocol, **pool_kwargs)
            owns = True
        elif pool_kwargs:
            raise DisaggConfigError(
                "pool kwargs are only accepted when DisaggClient builds "
                "the pool itself (pass urls, not a client)")
        try:
            super().__init__(client, **kwargs)
        except BaseException:
            if owns:
                client.close()
            raise
        self._owns = owns

    def close(self) -> None:
        if self._owns:
            self.inner.close()

    def __enter__(self) -> "DisaggClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- session -------------------------------------------------------------
    def generate_stream(self, tokens, max_tokens: Optional[int] = None,
                        end_id: Optional[int] = None, *,
                        priority: int = 0,
                        client_timeout: Optional[float] = None,
                        request_id: str = ""):
        """One disaggregated generation session. Yields
        ``{"NEXT_TOKEN": int, "INDEX": int}`` events, each token exactly
        once, bit-exact vs monolithic ``tiny_lm_generate`` over the same
        prompt — through role fallback and re-prefill recovery alike."""
        prompt = self._normalize_prompt(tokens)
        budget_tokens = int(max_tokens if max_tokens is not None
                            else self.DEFAULT_MAX_TOKENS)
        if budget_tokens < 1:
            raise DisaggError("max_tokens must be >= 1")
        return self._run(prompt, budget_tokens,
                         int(end_id) if end_id is not None else None,
                         priority, client_timeout, request_id)

    def _run(self, prompt, max_tokens, end_id, priority, client_timeout,
             request_id):
        tel = self.inner.telemetry()
        scratch = _flight.layer_begin(tel, "disagg", self.decode_model)
        error: Optional[BaseException] = None
        try:
            yield from self._run_session(
                prompt, max_tokens, end_id, priority, client_timeout,
                request_id)
        except BaseException as e:
            error = e
            raise
        finally:
            if scratch is not None:
                if error is not None:
                    _flight.layer_commit(tel, scratch, error=error)
                else:
                    _flight.layer_commit(tel, scratch)

    def _run_session(self, prompt, max_tokens, end_id, priority,
                     client_timeout, request_id):
        inner = self.inner
        pool = inner.pool
        budget = AttemptBudget(inner._budget_policy, client_timeout)
        emitted: List[int] = []
        handoff: Optional[KvHandoff] = None
        d_token = None
        ctrl = inner.admission()

        # ---- first prefill (typed fallback while nothing streamed yet)
        try:
            handoff = self._prefill_leg(prompt, budget, priority,
                                        request_id)
        except (NoEndpointAvailableError, AdmissionRejected) as e:
            if not self._is_role_outage(e):
                raise
            yield from self._fallback(prompt, max_tokens, end_id,
                                      self.prefill_role, e, request_id)
            return

        dead: List[str] = []
        attempts_left = max(1, inner._max_failover_attempts)
        try:
            while not self._finished(emitted, max_tokens, end_id):
                # ---- pick a decode replica (excluding known-dead ones)
                try:
                    exclude = [ep for ep in pool.endpoints
                               if ep.url in dead]
                    dep = pool.select(role=self.decode_role,
                                      exclude=exclude)
                except (NoEndpointAvailableError, AdmissionRejected) as e:
                    if not emitted and not dead and self._is_role_outage(e):
                        handoff.release()
                        handoff = None
                        yield from self._fallback(
                            prompt, max_tokens, end_id, self.decode_role,
                            e, request_id)
                        return
                    raise DecodeAbandoned(
                        dead[-1] if dead else "<none>", len(emitted), e)

                # ---- verified handoff: digest re-checked at the last
                # moment before any token can be derived from the bytes
                handoff.verify(dep.url)
                issued = inner.arena().ensure_registered(
                    dep.client, handoff.lease._region)
                _flight.note(
                    "disagg", "register_check", url=dep.url,
                    region=handoff.region, issued=issued)
                _flight.note(
                    "disagg", "verify", url=dep.url,
                    region=handoff.region, digest=handoff.digest)
                _flight.note(
                    "disagg", "route", leg="decode", url=dep.url,
                    role=self.decode_role, resume_at=len(emitted))

                if ctrl is not None:
                    d_token = ctrl.acquire(priority or 0, budget.deadline,
                                           lane=DECODE_LANE)
                stream = inner.pinned_generate_stream(
                    dep.url, self.decode_model,
                    self._decode_payload(handoff, emitted, max_tokens,
                                         end_id),
                    request_id=request_id)
                try:
                    for event in stream:
                        accepted = self._accept_event(event, emitted,
                                                      dep.url)
                        if accepted is None:
                            continue
                        token, index = accepted
                        yield {"NEXT_TOKEN": token, "INDEX": index}
                    return  # stream drained: the session is complete
                except (DisaggError, GeneratorExit):
                    raise
                except Exception as e:
                    domain = classify_fault(e)
                    if domain not in (CONNECT, TRANSIENT, TIMEOUT):
                        raise  # an application answer, not a dead replica
                    dead.append(dep.url)
                    attempts_left -= 1
                    _flight.note(
                        "disagg", "decode_died", url=dep.url,
                        emitted=len(emitted), domain=domain,
                        attempts_left=attempts_left)
                    if attempts_left <= 0:
                        raise DecodeAbandoned(dep.url, len(emitted), e)
                    # ---- re-prefill recovery: prefill is idempotent, so
                    # prompt + emitted reproduces the lost replica's exact
                    # cache on a fresh one — all under the SAME budget
                    if self._finished(emitted, max_tokens, end_id):
                        return  # died after the final token: nothing lost
                    handoff.release()
                    handoff = None
                    if d_token is not None:
                        d_token.release()
                        d_token = None
                    _flight.note("disagg", "reprefill",
                                 emitted=len(emitted), lost=dep.url)
                    try:
                        handoff = self._prefill_leg(
                            prompt + emitted, budget, priority, request_id)
                    except Exception as e2:
                        raise DecodeAbandoned(dep.url, len(emitted),
                                              e2) from e2
                finally:
                    if d_token is not None:
                        d_token.release()
                        d_token = None
        finally:
            if handoff is not None:
                handoff.release()

    # -- legs ----------------------------------------------------------------
    def _prefill_leg(self, tokens_full: List[int], budget: AttemptBudget,
                     priority: int, request_id: str) -> KvHandoff:
        """One pinned prefill infer on a prefill-role replica; the KV
        output lands in an arena slab and comes back as a verified
        :class:`KvHandoff` (caller owns its lease)."""
        inner = self.inner
        remaining = budget.attempt_timeout_s()
        ep = inner.pool.select(role=self.prefill_role)
        _flight.note("disagg", "route", leg="prefill", url=ep.url,
                     role=self.prefill_role, tokens=len(tokens_full))
        if self._kv_meta is None:
            self._kv_meta = self._kv_contract(
                ep.client.get_model_metadata(self.prefill_model))
        datatype, shape = self._kv_meta
        nbytes = self._kv_nbytes(datatype, shape)

        inp = InferInput("TOKENS", [1, len(tokens_full)], "INT32")
        inp.set_data_from_numpy(np.asarray([tokens_full], dtype=np.int32))
        kv_out = inner.arena().request_output("KV", nbytes)
        outputs = [kv_out, InferRequestedOutput("NEXT_TOKEN"),
                   InferRequestedOutput("POS")]

        ctrl = inner.admission()
        token = None
        if ctrl is not None:
            token = ctrl.acquire(priority or 0, budget.deadline,
                                 lane=PREFILL_LANE)
        t0 = time.monotonic()
        try:
            kw: Dict[str, Any] = {"request_id": request_id}
            if remaining is not None:
                kw["client_timeout"] = remaining
            result = inner.pinned_infer(ep.url, self.prefill_model, [inp],
                                        outputs=outputs, **kw)
        except BaseException as e:
            kv_out.release_arena_lease()
            if token is not None:
                inner._admission_settle(token, t0, e)
            raise
        if token is not None:
            inner._admission_settle(token, t0, None)
        return self._build_handoff(result, kv_out, datatype, shape,
                                   nbytes, len(tokens_full), ep.url)

    def _fallback(self, prompt, max_tokens, end_id, role: str,
                  cause: BaseException, request_id: str):
        """Typed degradation to monolithic single-replica serving."""
        reason = self._fallback_reason(cause)
        self.inner.pool.emit(RoleFallback("", role, reason))
        _flight.note("disagg", "fallback", role=role, reason=reason,
                     model=self.fallback_model)
        inputs: Dict[str, Any] = {"TOKENS": [list(prompt)],
                                  "MAX_TOKENS": int(max_tokens)}
        if end_id is not None:
            inputs["END_ID"] = int(end_id)
        for event in self.inner.generate_stream(
                self.fallback_model, inputs, request_id=request_id):
            yield {"NEXT_TOKEN": int(event["NEXT_TOKEN"]),
                   "INDEX": int(event["INDEX"])}


class AioDisaggClient(_DisaggBase):
    """Asyncio twin of :class:`DisaggClient` — same protocol, same typed
    faults, async generator sessions over an
    :class:`~client_tpu.pool.AioPoolClient`."""

    _AIO = True

    def __init__(self, client: Union[AioPoolClient, Sequence], *,
                 protocol: str = "http", **kwargs):
        pool_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                       if k not in ("prefill_model", "decode_model",
                                    "fallback_model", "prefill_role",
                                    "decode_role")}
        owns = False
        if not hasattr(client, "infer"):
            specs = [u if isinstance(u, EndpointSpec) else EndpointSpec(u)
                     for u in client]
            pool_kwargs.setdefault("shm_arena", True)
            client = AioPoolClient(specs, protocol=protocol, **pool_kwargs)
            owns = True
        elif pool_kwargs:
            raise DisaggConfigError(
                "pool kwargs are only accepted when AioDisaggClient builds "
                "the pool itself (pass urls, not a client)")
        try:
            super().__init__(client, **kwargs)
        except BaseException:
            if owns:
                # close() is a coroutine on the aio pool; schedule-free
                # best effort is wrong here — surface the config error,
                # the caller never saw the client
                import asyncio

                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                if loop is not None:
                    loop.create_task(client.close())
            raise
        self._owns = owns

    async def close(self) -> None:
        if self._owns:
            await self.inner.close()

    async def __aenter__(self) -> "AioDisaggClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- session -------------------------------------------------------------
    def generate_stream(self, tokens, max_tokens: Optional[int] = None,
                        end_id: Optional[int] = None, *,
                        priority: int = 0,
                        client_timeout: Optional[float] = None,
                        request_id: str = ""):
        prompt = self._normalize_prompt(tokens)
        budget_tokens = int(max_tokens if max_tokens is not None
                            else self.DEFAULT_MAX_TOKENS)
        if budget_tokens < 1:
            raise DisaggError("max_tokens must be >= 1")
        return self._run(prompt, budget_tokens,
                         int(end_id) if end_id is not None else None,
                         priority, client_timeout, request_id)

    async def _run(self, prompt, max_tokens, end_id, priority,
                   client_timeout, request_id):
        tel = self.inner.telemetry()
        scratch = _flight.layer_begin(tel, "disagg", self.decode_model)
        error: Optional[BaseException] = None
        try:
            async for event in self._run_session(
                    prompt, max_tokens, end_id, priority, client_timeout,
                    request_id):
                yield event
        except BaseException as e:
            error = e
            raise
        finally:
            if scratch is not None:
                if error is not None:
                    _flight.layer_commit(tel, scratch, error=error)
                else:
                    _flight.layer_commit(tel, scratch)

    async def _run_session(self, prompt, max_tokens, end_id, priority,
                           client_timeout, request_id):
        inner = self.inner
        pool = inner.pool
        budget = AttemptBudget(inner._budget_policy, client_timeout)
        emitted: List[int] = []
        handoff: Optional[KvHandoff] = None
        d_token = None
        ctrl = inner.admission()

        try:
            handoff = await self._prefill_leg(prompt, budget, priority,
                                              request_id)
        except (NoEndpointAvailableError, AdmissionRejected) as e:
            if not self._is_role_outage(e):
                raise
            async for event in self._fallback(
                    prompt, max_tokens, end_id, self.prefill_role, e,
                    request_id):
                yield event
            return

        dead: List[str] = []
        attempts_left = max(1, inner._max_failover_attempts)
        try:
            while not self._finished(emitted, max_tokens, end_id):
                try:
                    exclude = [ep for ep in pool.endpoints
                               if ep.url in dead]
                    dep = pool.select(role=self.decode_role,
                                      exclude=exclude)
                except (NoEndpointAvailableError, AdmissionRejected) as e:
                    if not emitted and not dead and self._is_role_outage(e):
                        handoff.release()
                        handoff = None
                        async for event in self._fallback(
                                prompt, max_tokens, end_id,
                                self.decode_role, e, request_id):
                            yield event
                        return
                    raise DecodeAbandoned(
                        dead[-1] if dead else "<none>", len(emitted), e)

                handoff.verify(dep.url)
                issued = await inner.arena().ensure_registered_async(
                    dep.client, handoff.lease._region)
                _flight.note(
                    "disagg", "register_check", url=dep.url,
                    region=handoff.region, issued=issued)
                _flight.note(
                    "disagg", "verify", url=dep.url,
                    region=handoff.region, digest=handoff.digest)
                _flight.note(
                    "disagg", "route", leg="decode", url=dep.url,
                    role=self.decode_role, resume_at=len(emitted))

                if ctrl is not None:
                    d_token = await ctrl.acquire_async(
                        priority or 0, budget.deadline, lane=DECODE_LANE)
                stream = inner.pinned_generate_stream(
                    dep.url, self.decode_model,
                    self._decode_payload(handoff, emitted, max_tokens,
                                         end_id),
                    request_id=request_id)
                try:
                    async for event in stream:
                        accepted = self._accept_event(event, emitted,
                                                      dep.url)
                        if accepted is None:
                            continue
                        token, index = accepted
                        yield {"NEXT_TOKEN": token, "INDEX": index}
                    return
                except (DisaggError, GeneratorExit):
                    raise
                except Exception as e:
                    domain = classify_fault(e)
                    if domain not in (CONNECT, TRANSIENT, TIMEOUT):
                        raise
                    dead.append(dep.url)
                    attempts_left -= 1
                    _flight.note(
                        "disagg", "decode_died", url=dep.url,
                        emitted=len(emitted), domain=domain,
                        attempts_left=attempts_left)
                    if attempts_left <= 0:
                        raise DecodeAbandoned(dep.url, len(emitted), e)
                    if self._finished(emitted, max_tokens, end_id):
                        return
                    handoff.release()
                    handoff = None
                    if d_token is not None:
                        d_token.release()
                        d_token = None
                    _flight.note("disagg", "reprefill",
                                 emitted=len(emitted), lost=dep.url)
                    try:
                        handoff = await self._prefill_leg(
                            prompt + emitted, budget, priority, request_id)
                    except Exception as e2:
                        raise DecodeAbandoned(dep.url, len(emitted),
                                              e2) from e2
                finally:
                    if d_token is not None:
                        d_token.release()
                        d_token = None
        finally:
            if handoff is not None:
                handoff.release()

    # -- legs ----------------------------------------------------------------
    async def _prefill_leg(self, tokens_full: List[int],
                           budget: AttemptBudget, priority: int,
                           request_id: str) -> KvHandoff:
        inner = self.inner
        remaining = budget.attempt_timeout_s()
        ep = inner.pool.select(role=self.prefill_role)
        _flight.note("disagg", "route", leg="prefill", url=ep.url,
                     role=self.prefill_role, tokens=len(tokens_full))
        if self._kv_meta is None:
            self._kv_meta = self._kv_contract(
                await ep.client.get_model_metadata(self.prefill_model))
        datatype, shape = self._kv_meta
        nbytes = self._kv_nbytes(datatype, shape)

        inp = InferInput("TOKENS", [1, len(tokens_full)], "INT32")
        inp.set_data_from_numpy(np.asarray([tokens_full], dtype=np.int32))
        kv_out = inner.arena().request_output("KV", nbytes)
        outputs = [kv_out, InferRequestedOutput("NEXT_TOKEN"),
                   InferRequestedOutput("POS")]

        ctrl = inner.admission()
        token = None
        if ctrl is not None:
            token = await ctrl.acquire_async(priority or 0, budget.deadline,
                                             lane=PREFILL_LANE)
        t0 = time.monotonic()
        try:
            kw: Dict[str, Any] = {"request_id": request_id}
            if remaining is not None:
                kw["client_timeout"] = remaining
            result = await inner.pinned_infer(
                ep.url, self.prefill_model, [inp], outputs=outputs, **kw)
        except BaseException as e:
            kv_out.release_arena_lease()
            if token is not None:
                inner._admission_settle(token, t0, e)
            raise
        if token is not None:
            inner._admission_settle(token, t0, None)
        return self._build_handoff(result, kv_out, datatype, shape,
                                   nbytes, len(tokens_full), ep.url)

    async def _fallback(self, prompt, max_tokens, end_id, role: str,
                        cause: BaseException, request_id: str):
        reason = self._fallback_reason(cause)
        self.inner.pool.emit(RoleFallback("", role, reason))
        _flight.note("disagg", "fallback", role=role, reason=reason,
                     model=self.fallback_model)
        inputs: Dict[str, Any] = {"TOKENS": [list(prompt)],
                                  "MAX_TOKENS": int(max_tokens)}
        if end_id is not None:
            inputs["END_ID"] = int(end_id)
        async for event in self.inner.generate_stream(
                self.fallback_model, inputs, request_id=request_id):
            yield {"NEXT_TOKEN": int(event["NEXT_TOKEN"]),
                   "INDEX": int(event["INDEX"])}
