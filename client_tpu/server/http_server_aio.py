"""aiohttp frontend for ServerCore: same v2 surface, event-loop concurrency.

A drop-in alternative to the threaded stdlib frontend (``http_server.py``)
for higher request rates: one event loop, blocking model execution offloaded
to a worker pool. Shares the request/response marshaling with the threaded
frontend.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from aiohttp import web

from .core import InferError, ServerCore
from .http_server import (
    _FAMILY,
    _generate_core_request,
    _generate_event,
    _generate_once,
    _sse_event,
    encode_infer_response,
    parse_infer_request,
)


def _json_response(obj: Any, status: int = 200) -> web.Response:
    return web.Response(
        body=json.dumps(obj, separators=(",", ":")).encode("utf-8"),
        status=status,
        content_type="application/json",
    )


def _error_response(e: Exception) -> web.Response:
    if isinstance(e, InferError):
        status = e.status
    elif isinstance(e, (json.JSONDecodeError, KeyError, ValueError, TypeError)):
        status = 400  # malformed payload, matching the threaded frontend
        return _json_response({"error": f"failed to parse request: {e}"}, status)
    else:
        status = 500
    return _json_response({"error": str(e)}, status)


class AioHttpInferenceServer:
    """An in-process v2 HTTP server on an asyncio event loop."""

    def __init__(self, core: ServerCore, port: int = 0, workers: int = 8):
        self.core = core
        self._port = port
        self._bound_port: Optional[int] = None
        self._executor = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="client_tpu_aio_server"
        )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._runner: Optional[web.AppRunner] = None

    # -- routes ------------------------------------------------------------
    def _app(self) -> web.Application:
        app = web.Application(client_max_size=2**31)
        core = self.core
        r = app.router

        async def live(request):
            return web.Response(status=200 if core.live else 503)

        async def ready(request):
            # drainable: close()/drain() flips core.ready so pool probes
            # route away before the listener disappears
            return web.Response(
                status=200 if (core.live and core.ready) else 503)

        r.add_get("/v2/health/live", live)
        r.add_get("/v2/health/ready", ready)

        async def metrics(request):
            # Prometheus scrape target; NOT gated on core.ready — a scraper
            # must see the drain (ready gauge -> 0), not connection errors
            return web.Response(
                body=core.metrics_registry().prometheus_text().encode(),
                content_type="text/plain",
                charset="utf-8",
            )

        r.add_get("/metrics", metrics)

        async def server_metadata(request):
            return _json_response(core.server_metadata())

        async def server_stats(request):
            return _json_response(core.statistics())

        async def trace_access(request):
            # traceparent-joined server spans (queue/compute ns +
            # wall_time_s): the doctor reads these to join its probe
            # trace and estimate client<->server clock skew
            return _json_response(core.access_records())

        r.add_get("/v2", server_metadata)
        r.add_get("/v2/models/stats", server_stats)
        r.add_get("/v2/trace/access", trace_access)

        async def model_route(request):
            name = request.match_info["name"]
            version = request.match_info.get("version", "")
            tail = request.match_info.get("tail", "")
            try:
                if tail == "ready":
                    return web.Response(
                        status=200 if core.model_ready(name, version) else 400
                    )
                if tail == "config":
                    return _json_response(core.model(name, version).config())
                if tail == "stats":
                    return _json_response(core.statistics(name, version))
                if tail == "":
                    return _json_response(core.model(name, version).metadata())
                return _json_response({"error": f"unknown route {tail}"}, 404)
            except Exception as e:
                return _error_response(e)

        async def infer_route(request):
            name = request.match_info["name"]
            version = request.match_info.get("version", "")
            try:
                body = await request.read()
                header_length = request.headers.get("Inference-Header-Content-Length")
                parsed = parse_infer_request(
                    body, int(header_length) if header_length is not None else None
                )
                traceparent = request.headers.get("traceparent")
                if traceparent:
                    # W3C trace context: the core attaches a server-side
                    # span joined on this trace id (access_records)
                    parsed["traceparent"] = traceparent
                requested = parsed.get("outputs")
                binary_default = bool(
                    parsed.get("binary_default")
                    or parsed.get("parameters", {}).get("binary_data_output", False)
                )
                loop = asyncio.get_running_loop()
                responses = await loop.run_in_executor(
                    self._executor, core.infer, name, version, parsed
                )
                body_out, json_size = encode_infer_response(
                    responses[0], requested, binary_default
                )
                headers = {}
                if json_size is not None:
                    headers["Inference-Header-Content-Length"] = str(json_size)
                    content_type = "application/octet-stream"
                else:
                    content_type = "application/json"
                orca = request.headers.get("endpoint-load-metrics-format")
                if orca in ("json", "text"):
                    headers["endpoint-load-metrics"] = core.orca_report(orca, name)
                return web.Response(
                    body=body_out, headers=headers, content_type=content_type
                )
            except InferError as e:
                return _error_response(e)
            except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
                return _json_response({"error": f"failed to parse request: {e}"}, 400)
            except Exception as e:
                return _json_response({"error": f"internal error: {e}"}, 500)

        r.add_get("/v2/models/{name}", model_route)
        r.add_get("/v2/models/{name}/{tail:config|ready|stats}", model_route)
        r.add_get("/v2/models/{name}/versions/{version}", model_route)
        r.add_get(
            "/v2/models/{name}/versions/{version}/{tail:config|ready|stats}",
            model_route,
        )
        r.add_post("/v2/models/{name}/infer", infer_route)
        r.add_post("/v2/models/{name}/versions/{version}/infer", infer_route)

        # -- generate extension (reference: tritonserver's HTTP
        # extension_generate; the LLM-serving JSON API genai-perf drives) --
        async def generate_route(request):
            name = request.match_info["name"]
            version = request.match_info.get("version", "")
            try:
                payload = await request.json()
                core_req = _generate_core_request(
                    core.model(name, version), payload)
                traceparent = request.headers.get("traceparent")
                if traceparent:
                    core_req["traceparent"] = traceparent
                loop = asyncio.get_running_loop()
                event = await loop.run_in_executor(
                    self._executor,
                    _generate_once, core, name, version, core_req)
            except Exception as e:
                return _error_response(e)
            return _json_response(event)

        async def generate_stream_route(request):
            name = request.match_info["name"]
            version = request.match_info.get("version", "")
            loop = asyncio.get_running_loop()
            sentinel = object()
            try:
                payload = await request.json()
                core_req = _generate_core_request(
                    core.model(name, version), payload)
                traceparent = request.headers.get("traceparent")
                if traceparent:
                    # W3C trace context: the generation joins the client's
                    # stream span in ServerCore.access_records
                    core_req["traceparent"] = traceparent
            except Exception as e:
                return _error_response(e)
            gen = core.infer_stream(name, version, core_req)
            fut = None

            def _close_gen():
                try:
                    gen.close()
                except Exception:
                    pass

            # From here every exit path — including a disconnect while the
            # FIRST response is still computing, or a failed prepare() —
            # runs the finally below, so the model's GeneratorExit path
            # (cancel stats bucket) fires eagerly rather than at GC.
            try:
                fut = loop.run_in_executor(
                    self._executor, next, gen, sentinel)
                try:
                    # shield: a client disconnect must not cancel the
                    # worker mid-frame (close() on an executing generator
                    # raises); the finally sequences close after the frame
                    first = await asyncio.shield(fut)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # request-level failure surfaces as an HTTP status,
                    # not an in-band event (mid-stream failures below ARE
                    # in-band)
                    return _error_response(e)
                resp = web.StreamResponse(
                    headers={"Content-Type": "text/event-stream",
                             "Cache-Control": "no-cache"})
                await resp.prepare(request)
                item = first
                while item is not sentinel:
                    await resp.write(_sse_event(_generate_event(item)))
                    fut = loop.run_in_executor(
                        self._executor, next, gen, sentinel)
                    try:
                        item = await asyncio.shield(fut)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        await resp.write(_sse_event({"error": str(e)}))
                        break
                await resp.write_eof()
                return resp
            finally:
                if fut is not None and not fut.done():
                    def _on_done(f):
                        if not f.cancelled():
                            f.exception()  # retrieve, silencing the warning
                        self._executor.submit(_close_gen)
                    fut.add_done_callback(_on_done)
                else:
                    self._executor.submit(_close_gen)

        r.add_post("/v2/models/{name}/generate", generate_route)
        r.add_post(
            "/v2/models/{name}/versions/{version}/generate", generate_route)
        r.add_post("/v2/models/{name}/generate_stream", generate_stream_route)
        r.add_post(
            "/v2/models/{name}/versions/{version}/generate_stream",
            generate_stream_route)

        async def repo_index(request):
            return _json_response(core.repository_index())

        async def repo_action(request):
            name = request.match_info["name"]
            action = request.match_info["action"]
            try:
                body = await request.read()
                if action == "load":
                    payload = json.loads(body) if body else {}
                    if not isinstance(payload, dict):
                        raise InferError("load request body must be a JSON object", 400)
                    core.load_model(
                        name, config=payload.get("parameters", {}).get("config")
                    )
                else:
                    core.unload_model(name)
                return _json_response({})
            except Exception as e:
                return _error_response(e)

        r.add_post("/v2/repository/index", repo_index)
        r.add_post("/v2/repository/models/{name}/{action:load|unload}", repo_action)

        async def shm_route(request):
            family = _FAMILY[request.match_info["family"]]
            # status GETs carry no {action} group in their route patterns
            action = request.match_info.get(
                "action", "status" if request.method == "GET" else ""
            )
            region = request.match_info.get("region", "")
            try:
                if action == "status":
                    return _json_response(core.region_status(family, region))
                body = await request.read()
                payload = json.loads(body) if body else {}
                if action == "register":
                    if family == "system":
                        core.register_system_region(
                            region, payload["key"], payload.get("offset", 0),
                            payload["byte_size"],
                        )
                    else:
                        core.register_handle_region(
                            family, region, payload["raw_handle"]["b64"],
                            payload.get("device_id", 0), payload["byte_size"],
                        )
                else:  # unregister
                    core.unregister_region(region or "", None if region else family)
                return _json_response({})
            except Exception as e:
                return _error_response(e)

        fam = "{family:systemsharedmemory|cudasharedmemory|tpusharedmemory}"
        r.add_get(f"/v2/{fam}/status", shm_route)
        r.add_get(f"/v2/{fam}/region/{{region}}/status", shm_route)
        for action in ("register", "unregister"):
            r.add_post(f"/v2/{fam}/region/{{region}}/{{action:{action}}}", shm_route)
        r.add_post(f"/v2/{fam}/{{action:unregister}}", shm_route)

        async def trace_route(request):
            if request.method == "POST":
                settings = json.loads(await request.read() or b"{}")
                core.trace_settings.update(settings)
            return _json_response(core.trace_settings)

        async def log_route(request):
            if request.method == "POST":
                settings = json.loads(await request.read() or b"{}")
                core.log_settings.update(settings)
            return _json_response(core.log_settings)

        r.add_get("/v2/trace/setting", trace_route)
        r.add_post("/v2/trace/setting", trace_route)
        r.add_get("/v2/models/{name}/trace/setting", trace_route)
        r.add_post("/v2/models/{name}/trace/setting", trace_route)
        r.add_get("/v2/logging", log_route)
        r.add_post("/v2/logging", log_route)
        return app

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self._bound_port or self._port

    @property
    def url(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "AioHttpInferenceServer":
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop

            async def bring_up():
                self._runner = web.AppRunner(self._app(), access_log=None)
                await self._runner.setup()
                site = web.TCPSite(self._runner, "127.0.0.1", self._port)
                await site.start()
                self._bound_port = site._server.sockets[0].getsockname()[1]
                self._started.set()

            loop.run_until_complete(bring_up())
            loop.run_forever()
            loop.run_until_complete(self._runner.cleanup())
            loop.close()

        self._thread = threading.Thread(
            target=run, name="client_tpu_aio_http_server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("aio http server failed to start")
        return self

    def drain(self, grace_s: float = 0.0) -> None:
        """Flip ``v2/health/ready`` to 503 and wait ``grace_s`` so pool
        ready-probes route away before the listener disappears; everything
        else keeps serving through the window. Note: ``core`` may be shared
        by several frontends; draining one drains them all."""
        self.core.ready = False
        if grace_s > 0:
            time.sleep(grace_s)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            # run() finishes with runner.cleanup(), which itself waits for
            # in-flight aiohttp handlers before closing the listener
            self._thread.join(timeout=10)
            self._thread = None
        self._executor.shutdown(wait=False)

    def close(self, grace_s: float = 0.5) -> None:
        """Graceful shutdown: drain, wait for pollers to route away, finish
        in-flight handlers, then close. SIGTERM handlers should call this."""
        self.drain(grace_s)
        self.stop()

    def __enter__(self) -> "AioHttpInferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
