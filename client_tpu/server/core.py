"""Protocol-neutral server core: model registry, shm data plane, stats, infer.

Both the HTTP and GRPC frontends marshal requests into the neutral dict shape
consumed by :meth:`ServerCore.infer`; the core resolves shared-memory
placement, executes the model, tracks statistics, and applies the
classification extension.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from collections import deque
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Any, Dict, List, Optional

import numpy as np

from ..models.base import Model
from ..utils import triton_to_np_dtype

_BUILTIN_SHM_FAMILIES = ("system", "cuda", "tpu")


class _Region:
    """A registered shared-memory region the server can read/write."""

    def __init__(
        self,
        name: str,
        family: str,
        key: str,
        offset: int,
        byte_size: int,
        device_id: int = 0,
        raw_handle: Optional[str] = None,
    ):
        self.name = name
        self.family = family
        self.key = key
        self.offset = offset
        self.byte_size = byte_size
        self.device_id = device_id
        self.raw_handle = raw_handle
        self._shm = None

    def _buffer(self) -> memoryview:
        if self._shm is None:
            from ..utils.shared_memory import attach_shared_memory

            self._shm = attach_shared_memory(self.key)
        return self._shm.buf

    def _check_range(self, nbytes: int, offset: int, op: str) -> int:
        if offset < 0 or nbytes < 0 or nbytes + offset > self.byte_size:
            raise ValueError(
                f"shared-memory {op} of {nbytes}B at offset {offset} exceeds "
                f"region '{self.name}' ({self.byte_size}B)"
            )
        return self.offset + offset

    def read(self, byte_size: int, offset: int) -> memoryview:
        base = self._check_range(byte_size, offset, "read")
        return self._buffer()[base : base + byte_size]

    def write(self, data: bytes, offset: int) -> None:
        base = self._check_range(len(data), offset, "write")
        self._buffer()[base : base + len(data)] = data

    def read_tensor(self, datatype: str, shape, byte_size: int, offset: int):
        """Materialize a tensor of ``datatype``/``shape`` from the region."""
        return _bytes_to_array(bytes(self.read(byte_size, offset)), datatype, shape)

    def write_tensor(self, arr, datatype: str, offset: int, limit: int, name: str = "?") -> int:
        """Serialize ``arr`` into the region; returns bytes written."""
        payload = _array_to_bytes(np.asarray(arr), datatype)
        if len(payload) > limit:
            raise InferError(
                f"output '{name}' ({len(payload)}B) exceeds shared-memory region "
                f"allotment of {limit}B", 400,
            )
        self.write(payload, offset)
        return len(payload)

    def close(self) -> None:
        if self._shm is not None:
            self._shm.close()
            self._shm = None

    def status(self) -> Dict[str, Any]:
        if self.family == "system":
            return {
                "name": self.name,
                "key": self.key,
                "offset": self.offset,
                "byte_size": self.byte_size,
            }
        return {
            "name": self.name,
            "device_id": self.device_id,
            "byte_size": self.byte_size,
        }


class _TpuRegion(_Region):
    """A registered tpu_shared_memory region — device-aware data plane.

    In-process registrations resolve to the client's own
    ``TpuSharedMemoryRegion`` object, so tensors bound with
    ``set_shared_memory_region_from_jax`` are handed to the model as live
    ``jax.Array``s (zero copies) and jax outputs are pinned back into the
    region's device cache the same way.
    """

    def __init__(self, name: str, raw_handle_b64: str, device_id: int, byte_size: int):
        from ..utils.tpu_shared_memory import attach_from_raw_handle

        self._region = attach_from_raw_handle(raw_handle_b64)
        super().__init__(
            name, "tpu", self._region.shm_key, 0, byte_size, device_id,
            raw_handle=raw_handle_b64,
        )

    def read(self, byte_size: int, offset: int) -> memoryview:
        return self._region.read_host(byte_size, offset)

    def write(self, data: bytes, offset: int) -> None:
        self._region.write_host(data, offset)

    def read_tensor(self, datatype: str, shape, byte_size: int, offset: int):
        if datatype == "BYTES":
            return super().read_tensor(datatype, shape, byte_size, offset)
        from ..utils import triton_to_np_dtype
        from ..utils.tpu_shared_memory import get_contents_as_jax

        nbytes = int(np.prod(shape)) * np.dtype(triton_to_np_dtype(datatype)).itemsize
        if nbytes > byte_size:
            raise InferError(
                f"shm input needs {nbytes}B for shape {list(shape)} {datatype} but "
                f"only {byte_size}B were supplied", 400,
            )
        return get_contents_as_jax(self._region, datatype, shape, offset)

    def write_tensor(self, arr, datatype: str, offset: int, limit: int, name: str = "?") -> int:
        from ..utils.tpu_shared_memory import (
            _is_jax_array,
            set_shared_memory_region_from_jax,
        )

        if datatype != "BYTES" and _is_jax_array(arr):
            nbytes = arr.dtype.itemsize * arr.size
            if nbytes > limit:
                raise InferError(
                    f"output '{name}' ({nbytes}B) exceeds shared-memory region "
                    f"allotment of {limit}B", 400,
                )
            set_shared_memory_region_from_jax(self._region, arr, offset)
            return nbytes
        return super().write_tensor(arr, datatype, offset, limit, name)

    def close(self) -> None:
        self._region.detach()


class _ModelStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.inference_count = 0
        self.execution_count = 0
        self.last_inference = 0
        self.success = [0, 0]  # count, ns
        self.fail = [0, 0]
        # client cancel/disconnect mid-stream: neither a success nor a
        # model failure (reference tracks cancelled requests separately)
        self.cancel = [0, 0]
        self.compute_infer = [0, 0]
        self.queue = [0, 0]
        self.batches: Dict[int, List[int]] = {}  # batch_size -> [count, ns]

    def record(self, ok: bool, total_ns: int, infer_ns: int, batch: int,
               executed: bool = True) -> None:
        """``executed=False`` for dynamically-batched requests: the model
        execution is counted once by record_batch, not once per request
        (reference semantics: execution_count < inference_count under
        batching)."""
        with self.lock:
            if ok:
                self.inference_count += batch
                if executed:
                    self.execution_count += 1
                    self.compute_infer[0] += 1
                    self.compute_infer[1] += infer_ns
                self.last_inference = int(time.time() * 1000)
                self.success[0] += 1
                self.success[1] += total_ns
            else:
                self.fail[0] += 1
                self.fail[1] += total_ns

    def record_cancel(self, total_ns: int) -> None:
        with self.lock:
            self.cancel[0] += 1
            self.cancel[1] += total_ns
            self.last_inference = int(time.time() * 1000)

    def record_batch(self, batch_size: int, exec_ns: int, queue_ns: int,
                     n_requests: int) -> None:
        """One dynamic-batcher execution (InferBatchStatistics feed).

        ``queue`` counts per REQUEST (Triton semantics — the average must
        be a request's wait, not the batch's summed waits)."""
        with self.lock:
            row = self.batches.setdefault(batch_size, [0, 0])
            row[0] += 1
            row[1] += exec_ns
            self.queue[0] += n_requests
            self.queue[1] += queue_ns
            self.execution_count += 1
            self.compute_infer[0] += 1
            self.compute_infer[1] += exec_ns

    def as_dict(self, name: str, version: str) -> Dict[str, Any]:
        with self.lock:
            return {
                "name": name,
                "version": version,
                "last_inference": self.last_inference,
                "inference_count": self.inference_count,
                "execution_count": self.execution_count,
                "inference_stats": {
                    "success": {"count": self.success[0], "ns": self.success[1]},
                    "fail": {"count": self.fail[0], "ns": self.fail[1]},
                    "cancel": {"count": self.cancel[0],
                               "ns": self.cancel[1]},
                    "queue": {"count": self.queue[0], "ns": self.queue[1]},
                    "compute_input": {"count": 0, "ns": 0},
                    "compute_infer": {
                        "count": self.compute_infer[0],
                        "ns": self.compute_infer[1],
                    },
                    "compute_output": {"count": 0, "ns": 0},
                },
                "batch_stats": [
                    {
                        "batch_size": size,
                        "compute_infer": {"count": row[0], "ns": row[1]},
                    }
                    for size, row in sorted(self.batches.items())
                ],
            }


class InferError(Exception):
    """Server-side inference failure with an HTTP-ish status code."""

    def __init__(self, msg: str, status: int = 400):
        super().__init__(msg)
        self.status = status


class ServerCore:
    """Registry + data plane + execution; shared by all protocol frontends."""

    def __init__(self, models: Optional[List[Model]] = None, name: str = "client_tpu_server"):
        self._name = name
        self._lock = threading.Lock()
        self._models: Dict[str, Model] = {}
        self._stats: Dict[str, _ModelStats] = {}
        self._regions: Dict[str, _Region] = {}
        self._batchers: Dict[str, Any] = {}  # model name -> (max_batch, DynamicBatcher)
        self.batch_timeout_s = 60.0  # future wait for one batched request
        self.trace_settings: Dict[str, Any] = {
            "trace_level": ["OFF"],
            "trace_rate": "1000",
            "trace_count": "-1",
            "log_frequency": "0",
            "trace_file": "",
            "trace_mode": "triton",
        }
        self.log_settings: Dict[str, Any] = {
            "log_file": "",
            "log_info": True,
            "log_warning": True,
            "log_error": True,
            "log_verbose_level": 0,
            "log_format": "default",
        }
        self.live = True
        # ready is the DRAINABLE half of health: frontends flip it false on
        # drain/close so pool ready-probes route away while in-flight
        # requests still complete (live stays true until the process exits)
        self.ready = True
        # rolling per-request trace records, populated when trace_level
        # includes TIMESTAMPS (Triton writes these to trace_file; we keep a
        # ring buffer and mirror to trace_file when one is configured)
        self._traces: List[Dict[str, Any]] = []
        self._trace_seq = 0
        self._trace_candidates = 0
        # W3C trace-context access records: every request that arrived with
        # a (valid) traceparent gets a server-side span joined on the same
        # trace id, so client phase timings and server queue/compute
        # timings line up (client_tpu.observe; scraped via /metrics)
        self._access: deque = deque(maxlen=1024)
        self._metrics_registry = None
        for m in models or []:
            self.add_model(m)

    # -- registry ----------------------------------------------------------
    def add_model(self, model: Model) -> None:
        with self._lock:
            self._models[model.name] = model
            self._stats.setdefault(model.name, _ModelStats())
        if hasattr(model, "bind"):  # ensembles resolve members at execute time
            model.bind(self.model)

    def model(self, name: str, version: str = "") -> Model:
        m = self._models.get(name)
        if m is None:
            raise InferError(f"Request for unknown model: '{name}' is not found", 400)
        if version and version not in m.versions:
            raise InferError(
                f"Request for unknown model: '{name}' version {version} is not found", 400
            )
        return m

    def model_ready(self, name: str, version: str = "") -> bool:
        try:
            return self.model(name, version).ready
        except InferError:
            return False

    def server_metadata(self) -> Dict[str, Any]:
        return {
            "name": self._name,
            "version": "2.x-client_tpu",
            "extensions": [
                "classification",
                "sequence",
                "model_repository",
                "model_repository(unload_dependents)",
                "schedule_policy",
                "model_configuration",
                "system_shared_memory",
                "cuda_shared_memory",
                "tpu_shared_memory",
                "binary_tensor_data",
                "parameters",
                "statistics",
                "trace",
                "logging",
            ],
        }

    def repository_index(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {
                    "name": m.name,
                    "version": m.versions[-1],
                    "state": "READY" if m.ready else "UNAVAILABLE",
                    "reason": "",
                }
                for m in self._models.values()
            ]

    def load_model(self, name: str, config: Optional[str] = None) -> None:
        model = self.model(name)
        if config:
            try:
                override = json.loads(config)
            except Exception as e:
                raise InferError(f"invalid config override: {e}", 400)
            if not isinstance(override, dict):
                raise InferError("config override must be a JSON object", 400)
            if override.get("name", name) != name:
                raise InferError(
                    "config override cannot rename the model", 400
                )
        else:
            # Triton semantics: a plain load reverts to the repository config
            override = {}
        model.config_override = override
        model.load()

    def unload_model(self, name: str) -> None:
        self.model(name).unload()

    def statistics(self, name: str = "", version: str = "") -> Dict[str, Any]:
        with self._lock:
            names = [name] if name else list(self._models.keys())
        out = []
        for n in names:
            m = self.model(n)
            out.append(self._stats[n].as_dict(n, version or m.versions[-1]))
        return {"model_stats": out}

    def _trace_enabled(self) -> bool:
        """Honors trace_level plus the trace_rate (sample 1-in-N) and
        trace_count (stop after N, -1 = unlimited) settings."""
        level = self.trace_settings.get("trace_level", [])
        if "TIMESTAMPS" not in level and "TENSORS" not in level:
            return False
        with self._lock:
            try:
                rate = max(int(self.trace_settings.get("trace_rate", 1) or 1), 1)
                count = int(self.trace_settings.get("trace_count", -1))
            except (TypeError, ValueError):
                rate, count = 1, -1
            if count >= 0 and self._trace_seq >= count:
                return False
            self._trace_candidates += 1
            return (self._trace_candidates - 1) % rate == 0

    def _record_trace(self, model_name: str, request_id: str, timestamps: Dict[str, int]) -> None:
        with self._lock:
            self._trace_seq += 1
            record = {
                "id": self._trace_seq,
                "model_name": model_name,
                "request_id": request_id,
                "timestamps": timestamps,
            }
            self._traces.append(record)
            if len(self._traces) > 1024:
                del self._traces[: len(self._traces) - 1024]
            trace_file = self.trace_settings.get("trace_file")
        if trace_file:
            try:
                with open(trace_file, "a") as f:
                    f.write(json.dumps(record) + "\n")
            except OSError:
                pass

    def recent_traces(self, count: int = 100) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._traces[-count:])

    # -- observability (client_tpu.observe counterpart) ----------------------
    def _observe_access(self, request: Dict[str, Any], model_name: str,
                        t0: int, t_infer: int, infer_ns: int,
                        responses: int = 1,
                        first_response_ns: Optional[int] = None) -> None:
        """Record a server-side span for a request that carried a W3C
        ``traceparent`` (frontends stash the header/metadata value under
        the reserved ``traceparent`` request key). ``client_span_id`` is
        the parent id from the header — the client's request span — so one
        trace id joins client phases to server queue/compute timings.
        Streamed (decoupled) requests additionally carry their response
        count and the server-side first-response latency, the join target
        for the client's StreamSpan TTFT."""
        traceparent = request.get("traceparent")
        if not traceparent:
            return
        from ..observe import make_span_id, parse_traceparent

        parsed = parse_traceparent(traceparent)
        if parsed is None:
            return
        trace_id, client_span_id, _sampled = parsed
        record = {
            "trace_id": trace_id,
            "client_span_id": client_span_id,
            "server_span_id": make_span_id(),
            "model_name": model_name,
            "request_id": request.get("id", ""),
            # recv -> compute-start: input resolution + batching queue
            "queue_ns": max(t_infer - t0, 0),
            "compute_ns": infer_ns,
            "total_ns": time.perf_counter_ns() - t0,
            "responses": responses,
            "wall_time_s": time.time(),
        }
        if first_response_ns is not None:
            record["first_response_ns"] = max(first_response_ns - t0, 0)
        with self._lock:
            self._access.append(record)

    def access_records(self, count: int = 100) -> List[Dict[str, Any]]:
        """The most recent traceparent-joined server spans (newest last)."""
        with self._lock:
            return list(self._access)[-count:]

    def metrics_registry(self):
        """The server's ``observe.MetricsRegistry`` (created on first use):
        live/ready gauges plus per-model request/latency series refreshed
        from the model statistics at scrape time. Both HTTP frontends serve
        its Prometheus rendering at ``GET /metrics``."""
        with self._lock:
            if self._metrics_registry is not None:
                return self._metrics_registry
        from ..observe import MetricsRegistry

        reg = MetricsRegistry()
        live = reg.gauge(
            "client_tpu_server_live", "Server liveness (1 live)")
        ready = reg.gauge(
            "client_tpu_server_ready",
            "Server readiness (0 while draining; live stays 1)")
        gauges = {
            "inference_count": reg.gauge(
                "client_tpu_server_inference_count",
                "Inferences completed (batched requests each count)",
                ("model",)),
            "execution_count": reg.gauge(
                "client_tpu_server_execution_count",
                "Model executions (execution < inference under batching)",
                ("model",)),
            "success": reg.gauge(
                "client_tpu_server_request_success_count",
                "Successful requests", ("model",)),
            "fail": reg.gauge(
                "client_tpu_server_request_fail_count",
                "Failed requests", ("model",)),
            "cancel": reg.gauge(
                "client_tpu_server_request_cancel_count",
                "Client-cancelled/abandoned streaming requests", ("model",)),
            "queue_seconds": reg.gauge(
                "client_tpu_server_queue_seconds",
                "Cumulative batching-queue wait", ("model",)),
            "compute_seconds": reg.gauge(
                "client_tpu_server_compute_seconds",
                "Cumulative model compute time", ("model",)),
        }
        traced = reg.gauge(
            "client_tpu_server_traced_requests",
            "Traceparent-joined access records currently buffered")

        def collect():
            live.set(1.0 if self.live else 0.0)
            ready.set(1.0 if (self.live and self.ready) else 0.0)
            for row in self.statistics()["model_stats"]:
                model = row["name"]
                gauges["inference_count"].labels(model).set(
                    row["inference_count"])
                gauges["execution_count"].labels(model).set(
                    row["execution_count"])
                stats = row["inference_stats"]
                gauges["success"].labels(model).set(stats["success"]["count"])
                gauges["fail"].labels(model).set(stats["fail"]["count"])
                gauges["cancel"].labels(model).set(stats["cancel"]["count"])
                gauges["queue_seconds"].labels(model).set(
                    stats["queue"]["ns"] / 1e9)
                gauges["compute_seconds"].labels(model).set(
                    stats["compute_infer"]["ns"] / 1e9)
            with self._lock:
                traced.set(len(self._access))

        reg.add_collector(collect)
        with self._lock:
            if self._metrics_registry is None:
                self._metrics_registry = reg
            return self._metrics_registry

    def orca_report(self, fmt: str, model_name: str = "") -> str:
        """Per-response load metrics in ORCA json or text form."""
        stats = self._stats.get(model_name)
        count = infer_ns = 0
        if stats is not None:
            with stats.lock:
                count = stats.inference_count
                infer_ns = (
                    stats.compute_infer[1] // max(stats.compute_infer[0], 1)
                )
        metrics = {
            "inference_count": count,
            "avg_compute_infer_us": infer_ns // 1000,
            "active_models": len(self._models),
        }
        if fmt == "json":
            return json.dumps({"named_metrics": metrics}, separators=(",", ":"))
        return ", ".join(f"named_metrics.{k}={v}" for k, v in metrics.items())

    # -- shared memory -----------------------------------------------------
    def register_system_region(self, name: str, key: str, offset: int, byte_size: int) -> None:
        self._register(_Region(name, "system", key, offset, byte_size))

    def register_handle_region(
        self, family: str, name: str, raw_handle_b64: str, device_id: int, byte_size: int
    ) -> None:
        """Register a tpu (or cuda-format) region from its serialized handle.

        tpu raw handles are base64 JSON descriptors produced by
        ``utils.tpu_shared_memory.get_raw_handle`` and carry the host shm key
        of the region's host window.
        """
        if family == "tpu":
            try:
                region: _Region = _TpuRegion(name, raw_handle_b64, device_id, byte_size)
            except Exception as e:
                raise InferError(f"failed to attach tpu shared-memory region: {e}", 400)
        else:
            try:
                desc = json.loads(base64.b64decode(raw_handle_b64))
                key = desc["shm_key"]
            except Exception as e:
                raise InferError(
                    f"failed to decode {family} shared-memory handle: {e}", 400
                )
            region = _Region(
                name,
                family,
                key,
                int(desc.get("offset", 0)),
                byte_size,
                device_id,
                raw_handle=raw_handle_b64,
            )
        self._register(region)

    def _register(self, region: _Region) -> None:
        with self._lock:
            existing = self._regions.get(region.name)
            if existing is not None:
                # Triton semantics: an active name must be unregistered first
                region.close()
                raise InferError(
                    f"shared memory region '{region.name}' already in manager",
                    400,
                )
            self._regions[region.name] = region

    def unregister_region(self, name: str = "", family: Optional[str] = None) -> None:
        with self._lock:
            if name:
                r = self._regions.pop(name, None)
                if r is not None:
                    r.close()
            else:
                for key in list(self._regions):
                    if family is None or self._regions[key].family == family:
                        self._regions.pop(key).close()

    def region_status(self, family: str, name: str = "") -> List[Dict[str, Any]]:
        with self._lock:
            return [
                r.status()
                for r in self._regions.values()
                if r.family == family and (not name or r.name == name)
            ]

    def _region(self, name: str) -> _Region:
        with self._lock:
            r = self._regions.get(name)
        if r is None:
            raise InferError(
                f"Unable to find shared memory region: '{name}'", 400
            )
        return r

    # -- inference ---------------------------------------------------------
    def infer(self, model_name: str, model_version: str, request: Dict[str, Any],
              decoupled_ok: bool = False):
        """Execute one inference.

        ``request``: {"id", "parameters", "inputs": [...], "outputs": [...]}
        where each input dict has name/datatype/shape plus exactly one of
        "array" (host ndarray) or "shm" ((region, byte_size, offset)).

        Returns a list of response dicts (len>1 only for decoupled models);
        each response: {"model_name","model_version","id","parameters",
        "outputs": [{name, datatype, shape, "array"|"shm"}]}.
        """
        t0 = time.perf_counter_ns()
        model = self.model(model_name, model_version)
        if not model.ready:
            raise InferError(f"Request for unknown model: '{model_name}' is not ready", 400)
        if model.decoupled and not decoupled_ok:
            raise InferError(
                f"model '{model_name}' is a decoupled model: use streaming inference", 400
            )
        if model.decoupled:
            # delegate to the incremental generator (it owns stats/tracing
            # for the decoupled path); materializing here keeps infer()'s
            # list-of-responses contract
            return list(self._decoupled_stream(
                model, model_name, model_version, request, t0))
        try:
            inputs = self._resolve_inputs(model, request)
            params = request.get("parameters", {})
            t_infer = time.perf_counter_ns()
            batched = False
            if self._batchable(model, params):
                batched = True
                try:
                    raw_responses = [
                        self._batcher_for(model).submit(inputs, params).result(
                            timeout=self.batch_timeout_s)
                    ]
                except FuturesTimeoutError:
                    raise InferError(
                        f"batched inference timed out after "
                        f"{self.batch_timeout_s:.0f}s (the execution may "
                        f"still complete server-side; raise "
                        f"core.batch_timeout_s for cold-compile workloads)",
                        504,
                    )
            else:
                raw_responses = [model.execute(inputs, params)]
            infer_ns = time.perf_counter_ns() - t_infer
        except InferError:
            self._stats[model_name].record(False, time.perf_counter_ns() - t0, 0, 0)
            raise
        except Exception as e:
            self._stats[model_name].record(False, time.perf_counter_ns() - t0, 0, 0)
            raise InferError(f"inference failed: {e}", 400)

        responses = []
        for raw in raw_responses:
            responses.append(
                self._build_response(model, model_version, request, raw)
            )
        self._trace_request(model_name, request, t0, t_infer, infer_ns)
        self._observe_access(request, model_name, t0, t_infer, infer_ns)
        batch = 1
        if responses and model.effective_max_batch_size():
            first = next(iter(raw_responses[0].values()))
            batch = int(first.shape[0]) if first.ndim else 1
        self._stats[model_name].record(
            True, time.perf_counter_ns() - t0, infer_ns, batch,
            executed=not batched)
        return responses

    def infer_stream(self, model_name: str, model_version: str,
                     request: Dict[str, Any]):
        """Incremental inference: a generator yielding response dicts AS the
        model produces them. For decoupled models every yield reaches the
        caller before the next response is computed — a streaming frontend
        that forwards each yield gives true time-to-first-token (the
        reference's decoupled transaction policy streams the same way:
        TRITONBACKEND_ResponseSend per response, not a batch at the end).
        Non-decoupled models yield their single infer() response."""
        model = self.model(model_name, model_version)
        if not model.decoupled:
            yield from self.infer(model_name, model_version, request)
            return
        if not model.ready:
            raise InferError(
                f"Request for unknown model: '{model_name}' is not ready", 400)
        yield from self._decoupled_stream(
            model, model_name, model_version, request, time.perf_counter_ns())

    def _decoupled_stream(self, model: Model, model_name: str,
                          model_version: str, request: Dict[str, Any],
                          t0: int):
        """Drive ``execute_decoupled`` lazily, building + yielding each
        response as it is produced. Owns stats and trace recording for the
        whole decoupled request (exactly-once, whether it completes, fails
        mid-stream, or the consumer abandons the generator)."""
        recorded = False

        def record(ok: bool, infer_ns: int):
            nonlocal recorded
            if recorded:
                return
            recorded = True
            # inference_count counts the REQUEST once, regardless of how
            # many responses streamed (reference decoupled semantics:
            # response count != request count)
            self._stats[model_name].record(
                ok, time.perf_counter_ns() - t0, infer_ns, 1 if ok else 0)

        try:
            inputs = self._resolve_inputs(model, request)
            params = request.get("parameters", {})
        except InferError:
            record(False, 0)
            raise
        except Exception as e:
            record(False, 0)
            raise InferError(f"inference failed: {e}", 400)

        t_infer = time.perf_counter_ns()
        gen = model.execute_decoupled(inputs, params)
        n_responses = 0
        t_first: Optional[int] = None
        try:
            for raw in gen:
                response = self._build_response(
                    model, model_version, request, raw)
                if t_first is None:
                    t_first = time.perf_counter_ns()
                n_responses += 1
                yield response
        except GeneratorExit:
            # consumer went away mid-stream (client cancel/disconnect):
            # a separate cancel bucket — counting it as success made
            # abandonment indistinguishable from completed generations
            self._stats[model_name].record_cancel(
                time.perf_counter_ns() - t0)
            raise
        except InferError:
            record(False, 0)
            raise
        except Exception as e:
            record(False, 0)
            raise InferError(f"inference failed: {e}", 400)
        infer_ns = time.perf_counter_ns() - t_infer
        record(True, infer_ns)
        self._trace_request(model_name, request, t0, t_infer, infer_ns)
        self._observe_access(request, model_name, t0, t_infer, infer_ns,
                             responses=n_responses,
                             first_response_ns=t_first)

    def _trace_request(self, model_name: str, request: Dict[str, Any],
                       t0: int, t_infer: int, infer_ns: int) -> None:
        """Shared per-request trace capture (sync infer + decoupled stream)."""
        if not self._trace_enabled():
            return
        self._record_trace(
            model_name,
            request.get("id", ""),
            {
                "request_start_ns": t0,
                "compute_start_ns": t_infer,
                "compute_end_ns": t_infer + infer_ns,
                "request_end_ns": time.perf_counter_ns(),
            },
        )

    # -- dynamic batching ---------------------------------------------------
    def _batchable(self, model: Model, params: Dict[str, Any]) -> bool:
        """Coalescing is for stateless, non-sequence, non-decoupled models
        that declared batch capacity; sequence requests must never merge."""
        return (
            model.effective_max_batch_size() > 1
            and not model.decoupled
            and not getattr(model, "stateful", False)
            and not params.get("sequence_id")
        )

    def _batcher_for(self, model: Model):
        from .batcher import DynamicBatcher

        max_batch = model.effective_max_batch_size()
        stale = None
        with self._lock:
            entry = self._batchers.get(model.name)
            if entry is not None and entry[0] == max_batch:
                return entry[1]
            stale = entry[1] if entry is not None else None
            stats = self._stats[model.name]
            batcher = DynamicBatcher(
                model.execute, max_batch, report=stats.record_batch)
            self._batchers[model.name] = (max_batch, batcher)
        if stale is not None:
            # max_batch_size changed via load override; close OUTSIDE the
            # core lock — close() joins the worker (seconds under load) and
            # every server operation takes this lock
            stale.close()
        return batcher

    def _resolve_inputs(self, model: Model, request: Dict[str, Any]) -> Dict[str, np.ndarray]:
        specs = {s.name: s for s in model.inputs()}
        out: Dict[str, np.ndarray] = {}
        for inp in request.get("inputs", []):
            name = inp["name"]
            spec = specs.get(name)
            if spec is None:
                raise InferError(
                    f"unexpected inference input '{name}' for model '{model.name}'", 400
                )
            datatype = inp.get("datatype", spec.datatype)
            if datatype != spec.datatype:
                raise InferError(
                    f"inference input '{name}' has datatype {datatype}; "
                    f"model expects {spec.datatype}", 400,
                )
            shape = inp.get("shape", [])
            if not spec.matches(shape):
                raise InferError(
                    f"unexpected shape {shape} for input '{name}' "
                    f"(model expects {spec.shape})", 400,
                )
            shm = inp.get("shm")
            if shm is not None:
                region_name, byte_size, offset = shm
                region = self._region(region_name)
                try:
                    region._check_range(byte_size, offset, "read")
                except ValueError as e:
                    raise InferError(str(e), 400)
                out[name] = region.read_tensor(datatype, shape, byte_size, offset)
            else:
                arr = inp.get("array")
                if arr is None:
                    raise InferError(f"input '{name}' has no data", 400)
                out[name] = arr
        missing = {s for s in set(specs) - set(out) if not specs[s].optional}
        if missing:
            raise InferError(
                f"expected {len(specs)} inputs but got {len(out)} inputs for "
                f"model '{model.name}' (missing: {sorted(missing)})", 400,
            )
        return out

    def _build_response(
        self, model: Model, model_version: str, request: Dict[str, Any],
        raw: Dict[str, np.ndarray],
    ) -> Dict[str, Any]:
        requested = request.get("outputs")
        out_specs: List[Dict[str, Any]] = []
        if requested:
            for r in requested:
                if r["name"] not in raw:
                    raise InferError(
                        f"unexpected inference output '{r['name']}' for model "
                        f"'{model.name}'", 400,
                    )
                out_specs.append(r)
        else:
            out_specs = [{"name": n} for n in raw.keys()]

        outputs = []
        for spec in out_specs:
            name = spec["name"]
            arr = raw[name]  # np.ndarray or jax.Array; stays on device if jax
            class_count = spec.get("classification", 0)
            if class_count:
                arr = _classification(
                    arr, class_count, model.labels(),
                    batched=model.effective_max_batch_size() > 0,
                )
                datatype = "BYTES"
            else:
                from ..utils import np_to_triton_dtype

                datatype = np_to_triton_dtype(arr.dtype)
            entry: Dict[str, Any] = {
                "name": name,
                "datatype": datatype,
                "shape": list(arr.shape),
            }
            shm = spec.get("shm")
            if shm is not None:
                region_name, byte_size, offset = shm
                written = self._region(region_name).write_tensor(
                    arr, datatype, offset, byte_size, name
                )
                entry["shm"] = (region_name, written, offset)
            else:
                entry["array"] = np.asarray(arr)
            outputs.append(entry)
        resp: Dict[str, Any] = {
            "model_name": model.name,
            "model_version": model_version or model.versions[-1],
            "outputs": outputs,
        }
        if request.get("id"):
            resp["id"] = request["id"]
        return resp


def _bytes_to_array(buf: bytes, datatype: str, shape) -> np.ndarray:
    from ..utils import deserialize_bf16_tensor, deserialize_bytes_tensor

    if datatype == "BYTES":
        return deserialize_bytes_tensor(buf).reshape(shape)
    if datatype == "BF16":
        return deserialize_bf16_tensor(buf).reshape(shape)
    return np.frombuffer(buf, dtype=triton_to_np_dtype(datatype)).reshape(shape)


def _array_to_bytes(arr: np.ndarray, datatype: str) -> bytes:
    from ..utils import serialize_bf16_tensor, serialize_byte_tensor

    if datatype == "BYTES":
        s = serialize_byte_tensor(arr)
        return s.item() if s.size else b""
    if datatype == "BF16":
        s = serialize_bf16_tensor(arr)
        return s.item() if s.size else b""
    return np.ascontiguousarray(arr).tobytes()


def _classification(
    arr, k: int, labels: Optional[List[str]], batched: bool = False
) -> np.ndarray:
    """classification extension: top-k "value:index[:label]" strings.

    Triton semantics: for batched models the first dim is the batch and each
    element's (flattened) remainder is its class vector; for non-batched
    models the whole (flattened) tensor is one class vector — e.g. densenet's
    [1000,1,1] output.

    When the model returned a device-resident jax.Array (the XLA model zoo
    does), ranking runs on-device via ``ops.topk_classification`` and only
    the k winners cross to the host — instead of pulling the whole class
    vector back for a host argsort. Device dtypes are <=32-bit under the
    default jax config, so no precision caveat applies on that path; ties
    break lowest-index-first there (a stable descending sort), while the
    host path keeps its historical highest-index-first order.
    """
    on_device = type(arr).__module__.startswith(("jax", "jaxlib"))
    if batched and arr.ndim >= 1:
        flat_batch = arr.reshape((arr.shape[0], -1))
    else:
        flat_batch = arr.reshape((1, -1))
    k = min(k, flat_batch.shape[-1])
    if on_device:
        from ..ops import topk_classification

        values, indices = topk_classification(flat_batch, k)
        values, indices = np.asarray(values), np.asarray(indices)
    else:
        flat_batch = np.asarray(flat_batch)
        indices = np.argsort(flat_batch, axis=-1)[:, ::-1][:, :k]
        values = np.take_along_axis(flat_batch, indices, axis=-1)
    rows = []
    for row_values, row_indices in zip(values, indices):
        entries = []
        for value, i in zip(row_values, row_indices):
            s = f"{value:f}:{i}"
            if labels and i < len(labels):
                s += f":{labels[i]}"
            entries.append(s.encode("utf-8"))
        rows.append(entries)
    out = np.array(rows, dtype=np.object_)
    if not batched:
        return out.reshape(-1)
    return out.reshape((arr.shape[0], k))
