"""In-process embedding entry points for non-Python hosts.

The C shim (``native/src/server_embed.cc``) embeds CPython, imports this
module, and calls these functions to host the inference server inside a
C/C++/Java process — the role the reference's **java-api-bindings** plays
for tritonserver (reference:
src/java-api-bindings/scripts/install_dependencies_and_build.sh builds
JavaCPP bindings over the tritonserver **C API**; here the C API is
``native/include/client_tpu/server_embed.h`` and the engine is this
framework's ServerCore + JAX).

Contract choices keep the FFI surface flat and stable:
- requests/responses cross the boundary as the KServe v2 HTTP body format
  (JSON header + binary tails + header-length), reusing the exact
  marshaling both the HTTP frontend and every client already speak;
- admin surfaces cross as JSON strings;
- handles are opaque integers (an index into a process-global table) so
  the C side never touches Python object lifetimes.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional, Tuple

from .core import InferError, ServerCore

_cores: Dict[int, dict] = {}
_next_handle = 1
_lock = threading.Lock()


def create(options_json: str = "") -> int:
    """Create a ServerCore; returns an opaque handle.

    ``options_json``: ``{"models": ["simple", ...]}`` selects models from
    the default zoo by name; empty/absent loads the full zoo.
    """
    from ..models import default_model_zoo

    global _next_handle
    opts = json.loads(options_json) if options_json.strip() else {}
    zoo = default_model_zoo()
    wanted = opts.get("models")
    if wanted is not None:
        by_name = {m.name: m for m in zoo}
        missing = [n for n in wanted if n not in by_name]
        if missing:
            raise ValueError(f"unknown models: {missing} "
                             f"(zoo: {sorted(by_name)})")
        zoo = [by_name[n] for n in wanted]
    core = ServerCore(zoo)
    with _lock:
        handle = _next_handle
        _next_handle += 1
        _cores[handle] = {"core": core, "http": None}
    return handle


def _entry(handle: int) -> dict:
    entry = _cores.get(handle)
    if entry is None:
        raise ValueError(f"invalid server handle {handle}")
    return entry


def infer(handle: int, model_name: str, model_version: str,
          body: bytes, header_length: int) -> Tuple[bytes, int]:
    """One inference round trip in the v2 two-part body format.

    ``header_length`` < 0 means the body is pure JSON. Returns
    ``(response_body, response_header_length)`` with header_length -1 when
    the response is pure JSON.
    """
    from .http_server import (
        encode_infer_response,
        infer_request_encoding_prefs,
        parse_infer_request,
    )

    core = _entry(handle)["core"]
    request = parse_infer_request(
        bytes(body), header_length if header_length >= 0 else None)
    requested, binary_default = infer_request_encoding_prefs(request)
    responses = core.infer(model_name, model_version, request)
    out, json_size = encode_infer_response(
        responses[0], requested, binary_default)
    return out, -1 if json_size is None else json_size


def metadata_json(handle: int, model_name: str = "") -> bytes:
    core = _entry(handle)["core"]
    # same documents the HTTP frontend serves (http_server.py GET routes)
    doc = (core.model(model_name).metadata() if model_name
           else core.server_metadata())
    return json.dumps(doc).encode()


def repository_index_json(handle: int) -> bytes:
    return json.dumps(_entry(handle)["core"].repository_index()).encode()


def statistics_json(handle: int, model_name: str = "") -> bytes:
    return json.dumps(_entry(handle)["core"].statistics(model_name)).encode()


def load_model(handle: int, model_name: str, config_json: str = "") -> None:
    _entry(handle)["core"].load_model(model_name, config_json or None)


def unload_model(handle: int, model_name: str) -> None:
    _entry(handle)["core"].unload_model(model_name)


def start_http(handle: int, port: int = 0) -> int:
    """Expose the embedded core over the network too; returns the port."""
    from .http_server import HttpInferenceServer

    entry = _entry(handle)
    if entry["http"] is None:
        entry["http"] = HttpInferenceServer(entry["core"], port=port).start()
    return entry["http"].port


def destroy(handle: int) -> None:
    with _lock:
        entry = _cores.pop(handle, None)
    if entry and entry["http"] is not None:
        entry["http"].stop()


def _selftest() -> str:
    """Exercised by the embed smoke binary before real traffic."""
    return "ok"
