"""GRPC frontend for ServerCore: ``inference.GRPCInferenceService``.

Serves the full 19-rpc v2 surface plus the Tpu shared-memory rpc pair and
bidi ``ModelStreamInfer`` (sequences + decoupled models), using generic
method handlers bound to the schema-driven wire codec — the server twin of
``client_tpu.grpc``.
"""

from __future__ import annotations

import time
from concurrent import futures
from typing import Any, Dict, List, Optional

import grpc
import numpy as np

from ..grpc import _messages as M
from ..grpc._infer import _CONTENTS_FIELD, from_infer_parameter, to_infer_parameter
from ..grpc._wire import decode_message, encode_message
from ..utils import triton_to_np_dtype
from .core import InferError, ServerCore, _array_to_bytes

_STATUS_OF_HTTP = {
    400: grpc.StatusCode.INVALID_ARGUMENT,
    404: grpc.StatusCode.NOT_FOUND,
    499: grpc.StatusCode.CANCELLED,
    500: grpc.StatusCode.INTERNAL,
    503: grpc.StatusCode.UNAVAILABLE,
}

_CONFIG_TYPE_OF_TRITON = {
    name: i
    for i, name in enumerate(M.CONFIG_DATATYPE_NAMES)
}


def _to_core_request(decoded: Dict[str, Any]) -> Dict[str, Any]:
    """ModelInferRequest dict -> the neutral ServerCore request shape."""
    params = {
        k: from_infer_parameter(v) for k, v in decoded.get("parameters", {}).items()
    }
    request: Dict[str, Any] = {
        "id": decoded.get("id", ""),
        "parameters": params,
        "inputs": [],
    }
    raw = decoded.get("raw_input_contents", [])
    raw_idx = 0
    for t in decoded.get("inputs", []):
        tp = {k: from_infer_parameter(v) for k, v in t.get("parameters", {}).items()}
        entry: Dict[str, Any] = {
            "name": t.get("name", ""),
            "datatype": t.get("datatype", ""),
            "shape": t.get("shape", []),
        }
        if "shared_memory_region" in tp:
            entry["shm"] = (
                tp["shared_memory_region"],
                tp.get("shared_memory_byte_size", 0),
                tp.get("shared_memory_offset", 0),
            )
        elif t.get("contents"):
            contents = t["contents"]
            field = _CONTENTS_FIELD.get(entry["datatype"])
            data = contents.get(field, []) if field else []
            if entry["datatype"] == "BYTES":
                arr = np.array(data, dtype=np.object_).reshape(entry["shape"])
            else:
                arr = np.array(
                    data, dtype=triton_to_np_dtype(entry["datatype"])
                ).reshape(entry["shape"])
            entry["array"] = arr
        else:
            if raw_idx >= len(raw):
                raise InferError(
                    f"input '{entry['name']}' has no data (raw_input_contents "
                    f"has {len(raw)} entries)", 400,
                )
            from .core import _bytes_to_array

            entry["array"] = _bytes_to_array(
                raw[raw_idx], entry["datatype"], entry["shape"]
            )
            raw_idx += 1
        request["inputs"].append(entry)

    outputs = []
    for o in decoded.get("outputs", []):
        op = {k: from_infer_parameter(v) for k, v in o.get("parameters", {}).items()}
        spec: Dict[str, Any] = {
            "name": o.get("name", ""),
            "binary": True,
            "classification": op.get("classification", 0),
        }
        if "shared_memory_region" in op:
            spec["shm"] = (
                op["shared_memory_region"],
                op.get("shared_memory_byte_size", 0),
                op.get("shared_memory_offset", 0),
            )
        outputs.append(spec)
    if outputs:
        request["outputs"] = outputs
    return request


def _encode_core_response(resp: Dict[str, Any], final: Optional[bool] = None) -> Dict[str, Any]:
    """Neutral core response -> ModelInferResponse dict."""
    out: Dict[str, Any] = {
        "model_name": resp.get("model_name", ""),
        "model_version": resp.get("model_version", ""),
    }
    if resp.get("id"):
        out["id"] = resp["id"]
    params = {k: to_infer_parameter(v) for k, v in (resp.get("parameters") or {}).items()}
    if final is not None:
        params["triton_final_response"] = {"bool_param": final}
    if params:
        out["parameters"] = params
    outputs = []
    raws: List[bytes] = []
    for o in resp.get("outputs", []):
        entry: Dict[str, Any] = {
            "name": o["name"],
            "datatype": o["datatype"],
            "shape": list(o["shape"]),
        }
        if "shm" in o:
            region, byte_size, offset = o["shm"]
            p = {
                "shared_memory_region": to_infer_parameter(region),
                "shared_memory_byte_size": to_infer_parameter(int(byte_size)),
            }
            if offset:
                p["shared_memory_offset"] = to_infer_parameter(int(offset))
            entry["parameters"] = p
        else:
            raws.append(_array_to_bytes(np.asarray(o["array"]), o["datatype"]))
        outputs.append(entry)
    out["outputs"] = outputs
    if raws:
        out["raw_output_contents"] = raws
    return out


class _Handlers(grpc.GenericRpcHandler):
    def __init__(self, core: ServerCore, verbose: bool = False):
        self._core = core
        self._verbose = verbose

    # -- routing -----------------------------------------------------------
    def service(self, handler_call_details):
        method = handler_call_details.method.rsplit("/", 1)[-1]
        specs = M.METHODS.get(method)
        if specs is None:
            return None
        req_spec, resp_spec = specs
        deserializer = lambda b: decode_message(req_spec, b)  # noqa: E731
        serializer = lambda d: encode_message(resp_spec, d)  # noqa: E731
        if method == "ModelStreamInfer":
            return grpc.stream_stream_rpc_method_handler(
                self._model_stream_infer,
                request_deserializer=deserializer,
                response_serializer=serializer,
            )
        fn = getattr(self, f"_{_snake(method)}", None)
        if fn is None:
            return None
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=deserializer, response_serializer=serializer
        )

    def _abort(self, context, e: Exception):
        if isinstance(e, InferError):
            context.abort(
                _STATUS_OF_HTTP.get(e.status, grpc.StatusCode.INVALID_ARGUMENT), str(e)
            )
        context.abort(grpc.StatusCode.INTERNAL, str(e))

    # -- health / metadata ---------------------------------------------------
    def _server_live(self, request, context):
        return {"live": self._core.live}

    def _server_ready(self, request, context):
        # drainable: drain()/close() flips core.ready so pool ready-probes
        # route away while in-flight RPCs still complete
        return {"ready": self._core.live and self._core.ready}

    def _model_ready(self, request, context):
        return {
            "ready": self._core.model_ready(
                request.get("name", ""), request.get("version", "")
            )
        }

    def _server_metadata(self, request, context):
        return self._core.server_metadata()

    def _model_metadata(self, request, context):
        try:
            return self._core.model(
                request.get("name", ""), request.get("version", "")
            ).metadata()
        except InferError as e:
            self._abort(context, e)

    def _model_config(self, request, context):
        try:
            cfg = self._core.model(
                request.get("name", ""), request.get("version", "")
            ).config()
        except InferError as e:
            self._abort(context, e)
        # JSON-config -> proto-config field shapes
        config = {
            "name": cfg["name"],
            "platform": cfg.get("platform", ""),
            "backend": cfg.get("backend", ""),
            "max_batch_size": cfg.get("max_batch_size", 0),
            "input": [
                {
                    "name": i["name"],
                    "data_type": _CONFIG_TYPE_OF_TRITON.get(i["data_type"], 0),
                    "dims": i["dims"],
                }
                for i in cfg.get("input", [])
            ],
            "output": [
                {
                    "name": o["name"],
                    "data_type": _CONFIG_TYPE_OF_TRITON.get(o["data_type"], 0),
                    "dims": o["dims"],
                }
                for o in cfg.get("output", [])
            ],
            "model_transaction_policy": {
                "decoupled": cfg.get("model_transaction_policy", {}).get("decoupled", False)
            },
        }
        return {"config": config}

    # -- inference -----------------------------------------------------------
    @staticmethod
    def _metadata_value(context, wanted: str) -> Optional[str]:
        """One invocation-metadata value (the GRPC twin of an HTTP
        request header), or None when the client did not send it."""
        for key, value in (context.invocation_metadata() or ()):
            if key == wanted:
                return value
        return None

    @classmethod
    def _traceparent_of(cls, context) -> Optional[str]:
        return cls._metadata_value(context, "traceparent")

    def _model_infer(self, request, context):
        try:
            core_req = _to_core_request(request)
            traceparent = self._traceparent_of(context)
            if traceparent:
                core_req["traceparent"] = traceparent
            model_name = request.get("model_name", "")
            responses = self._core.infer(
                model_name, request.get("model_version", ""), core_req
            )
            orca_format = self._metadata_value(
                context, "endpoint-load-metrics-format")
            if orca_format in ("json", "text"):
                # ORCA per-response load metrics ride trailing metadata on
                # GRPC (the header transport HTTP doesn't have)
                context.set_trailing_metadata((
                    ("endpoint-load-metrics",
                     self._core.orca_report(orca_format, model_name)),
                ))
            return _encode_core_response(responses[0])
        except InferError as e:
            self._abort(context, e)

    def _model_stream_infer(self, request_iterator, context):
        # triton_grpc_error mode (reference README.md:569-590): when the
        # client sets this metadata key, stream errors surface as true grpc
        # statuses (terminating the stream) instead of in-band messages
        grpc_error_mode = any(
            key == "triton_grpc_error" and str(value).lower() == "true"
            for key, value in (context.invocation_metadata() or ())
        )
        traceparent = self._traceparent_of(context)
        for request in request_iterator:
            model_name = request.get("model_name", "")
            try:
                core_req = _to_core_request(request)
                if traceparent:
                    # stream-level metadata: every request on the stream
                    # joins the same client trace id
                    core_req["traceparent"] = traceparent
                want_final = bool(
                    core_req["parameters"].get("triton_enable_empty_final_response")
                )
                model = self._core.model(model_name, request.get("model_version", ""))
                # incremental: each decoupled response hits the wire as the
                # model yields it (true streaming TTFT), not after the full
                # generation materializes
                stream = self._core.infer_stream(
                    model_name, request.get("model_version", ""), core_req
                )
                try:
                    for resp in stream:
                        # with the empty-final opt-in, EVERY response carries
                        # an explicit triton_final_response (false on
                        # decoupled intermediates — reference semantics;
                        # clients may default absent to final, so omission
                        # is not a safe "not final")
                        final = (not model.decoupled) if want_final else None
                        yield {"infer_response": _encode_core_response(resp, final=final)}
                finally:
                    # a client cancel closes THIS generator at the yield
                    # above; close the core stream eagerly (not at GC) so
                    # the cancel bucket is recorded before the RPC unwinds
                    stream.close()
                if want_final and model.decoupled:
                    empty: Dict[str, Any] = {
                        "model_name": model_name,
                        "model_version": request.get("model_version", "") or model.versions[-1],
                        "outputs": [],
                    }
                    if request.get("id"):
                        empty["id"] = request["id"]
                    yield {"infer_response": _encode_core_response(empty, final=True)}
            except Exception as e:
                if grpc_error_mode:
                    code = (
                        _STATUS_OF_HTTP.get(e.status, grpc.StatusCode.INVALID_ARGUMENT)
                        if isinstance(e, InferError)
                        else grpc.StatusCode.INTERNAL
                    )
                    context.abort(code, str(e))
                # in-band (default semantics); the request id rides in the
                # otherwise-empty infer_response so clients can attribute
                # the error to the exact request (reconnecting streams
                # retire its pending entry precisely instead of guessing)
                out: Dict[str, Any] = {"error_message": str(e)}
                if request.get("id"):
                    out["infer_response"] = {"id": request["id"]}
                yield out

    # -- repository ----------------------------------------------------------
    def _repository_index(self, request, context):
        return {"models": self._core.repository_index()}

    def _repository_model_load(self, request, context):
        try:
            params = request.get("parameters", {})
            config = params.get("config", {}).get("string_param")
            self._core.load_model(request.get("model_name", ""), config=config)
        except InferError as e:
            self._abort(context, e)
        return {}

    def _repository_model_unload(self, request, context):
        try:
            self._core.unload_model(request.get("model_name", ""))
        except InferError as e:
            self._abort(context, e)
        return {}

    # -- statistics / trace / log ---------------------------------------------
    def _model_statistics(self, request, context):
        try:
            return self._core.statistics(
                request.get("name", ""), request.get("version", "")
            )
        except InferError as e:
            self._abort(context, e)

    def _trace_setting(self, request, context):
        for key, value in request.get("settings", {}).items():
            self._core.trace_settings[key] = value.get("value", [])
        out = {}
        for key, value in self._core.trace_settings.items():
            out[key] = {"value": value if isinstance(value, list) else [str(value)]}
        return {"settings": out}

    def _log_settings(self, request, context):
        for key, value in request.get("settings", {}).items():
            self._core.log_settings[key] = from_infer_parameter(value)
        out = {}
        for key, value in self._core.log_settings.items():
            if isinstance(value, bool):
                out[key] = {"bool_param": value}
            elif isinstance(value, int):
                out[key] = {"uint32_param": value}
            else:
                out[key] = {"string_param": str(value)}
        return {"settings": out}

    # -- shared memory --------------------------------------------------------
    def _system_shared_memory_status(self, request, context):
        regions = self._core.region_status("system", request.get("name", ""))
        return {"regions": {r["name"]: r for r in regions}}

    def _system_shared_memory_register(self, request, context):
        try:
            self._core.register_system_region(
                request.get("name", ""),
                request.get("key", ""),
                request.get("offset", 0),
                request.get("byte_size", 0),
            )
        except InferError as e:
            self._abort(context, e)
        return {}

    def _system_shared_memory_unregister(self, request, context):
        self._core.unregister_region(request.get("name", ""), None if request.get("name") else "system")
        return {}

    def _device_shm_status(self, family, request):
        regions = self._core.region_status(family, request.get("name", ""))
        return {"regions": {r["name"]: r for r in regions}}

    def _device_shm_register(self, family, request, context):
        try:
            raw = request.get("raw_handle", b"")
            self._core.register_handle_region(
                family,
                request.get("name", ""),
                raw.decode("ascii") if isinstance(raw, bytes) else raw,
                request.get("device_id", 0),
                request.get("byte_size", 0),
            )
        except InferError as e:
            self._abort(context, e)
        return {}

    def _cuda_shared_memory_status(self, request, context):
        return self._device_shm_status("cuda", request)

    def _cuda_shared_memory_register(self, request, context):
        return self._device_shm_register("cuda", request, context)

    def _cuda_shared_memory_unregister(self, request, context):
        self._core.unregister_region(request.get("name", ""), None if request.get("name") else "cuda")
        return {}

    def _tpu_shared_memory_status(self, request, context):
        return self._device_shm_status("tpu", request)

    def _tpu_shared_memory_register(self, request, context):
        return self._device_shm_register("tpu", request, context)

    def _tpu_shared_memory_unregister(self, request, context):
        self._core.unregister_region(request.get("name", ""), None if request.get("name") else "tpu")
        return {}


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class GrpcInferenceServer:
    """An in-process v2 GRPC server bound to localhost."""

    def __init__(self, core: ServerCore, port: int = 0, max_workers: int = 8,
                 verbose: bool = False, compression=None, credentials=None):
        """``compression``: a ``grpc.Compression`` value (e.g. ``Gzip``) to
        compress responses for clients that advertise support — exercises
        clients' grpc-encoding decompression paths end-to-end.
        ``credentials``: a ``grpc.ServerCredentials`` (ssl_server_credentials)
        to serve TLS instead of cleartext h2c."""
        self.core = core
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="client_tpu_grpc_server"
            ),
            options=[
                ("grpc.max_send_message_length", 2**31 - 1),
                ("grpc.max_receive_message_length", 2**31 - 1),
            ],
            compression=compression,
        )
        self._server.add_generic_rpc_handlers((_Handlers(core, verbose),))
        if credentials is not None:
            self._port = self._server.add_secure_port(
                f"127.0.0.1:{port}", credentials
            )
        else:
            self._port = self._server.add_insecure_port(f"127.0.0.1:{port}")

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"127.0.0.1:{self._port}"

    def start(self) -> "GrpcInferenceServer":
        self._server.start()
        return self

    def drain(self, grace_s: float = 0.0) -> None:
        """Flip ``ServerReady`` to false and wait ``grace_s`` so pool
        ready-probes route away before the port closes. The server keeps
        serving (including ready-racing requests) during the window. Note:
        ``core`` may be shared by several frontends; draining one drains
        them all."""
        self.core.ready = False
        if grace_s > 0:
            time.sleep(grace_s)

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self._server.stop(grace).wait()

    def close(self, grace_s: float = 0.5) -> None:
        """Graceful shutdown: drain, wait for pollers to route away, let
        in-flight RPCs finish (grpc's own stop grace), then release the
        port. SIGTERM handlers should call this, not ``stop``."""
        self.drain(grace_s)
        self.stop(grace=10.0)

    def __enter__(self) -> "GrpcInferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
