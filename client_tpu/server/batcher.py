"""Dynamic batcher: coalesce concurrent single requests into MXU-sized
batches.

The scheduler component of the serving stack (tritonserver's dynamic
batcher role — the reference *client* repo exposes it only through
`InferBatchStatistics` in the protocol, which this feeds): batching is THE
TPU throughput lever, because an [8, ...] matmul costs barely more than an
[1, ...] one on the systolic array until the batch fills the MXU tile.

Mechanics: requests enter a queue; the worker pops the first, then keeps
collecting until ``max_batch`` requests are in hand or ``max_delay_s``
passes (latency bound). Compatible requests — same input names, dtypes,
and per-request non-batch dims — are stacked along axis 0, executed ONCE,
and the output rows are scattered back to each caller's Future. A request
incompatible with the rest of the window simply forms its own group:
nothing blocks behind shape mismatches.

Eligibility is decided by the core (stateless, non-decoupled models with
``max_batch_size > 1``; shm-bound and sequence requests bypass).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Tuple

import numpy as np


class _Pending:
    __slots__ = ("inputs", "parameters", "future", "enqueued_ns", "rows")

    def __init__(self, inputs, parameters):
        self.inputs = inputs
        self.parameters = parameters
        self.future: Future = Future()
        self.enqueued_ns = time.perf_counter_ns()
        # rows this request contributes to the stacked batch (axis 0)
        first = next(iter(inputs.values()))
        self.rows = int(first.shape[0]) if first.ndim else 1


def _compat_key(inputs: Dict[str, np.ndarray],
                parameters: Dict[str, Any]) -> Tuple:
    """Requests merge ONLY when their inputs line up AND their parameters
    are identical — execute() may honor any parameter, so merging across
    differing parameters would silently compute under the wrong ones."""
    return (
        tuple(sorted(
            (name, str(arr.dtype), arr.shape[1:])
            for name, arr in inputs.items())),
        repr(sorted(parameters.items(), key=lambda kv: kv[0])),
    )


class DynamicBatcher:
    """Per-model batching queue in front of ``execute``.

    ``report``: optional callback ``(batch_rows, exec_ns, queue_ns_total,
    n_requests)`` invoked once per executed batch — the core feeds it into
    the protocol's ``InferBatchStatistics``.
    """

    def __init__(
        self,
        execute: Callable[[Dict[str, np.ndarray], Dict[str, Any]], Dict[str, np.ndarray]],
        max_batch: int,
        max_delay_s: float = 0.002,
        max_queue: int = 1024,
        report: Callable[[int, int, int, int], None] = None,
    ):
        self._execute = execute
        self._max_batch = max(int(max_batch), 1)
        self._max_delay_s = max_delay_s
        self._report = report
        self._queue: "queue.Queue[_Pending]" = queue.Queue(maxsize=max_queue)
        self._closed = False
        self._carry: _Pending = None  # didn't fit the last window's cap
        self._worker = threading.Thread(
            target=self._run, name="dynamic-batcher", daemon=True)
        self._worker.start()

    # -- caller side --------------------------------------------------------
    def submit(self, inputs: Dict[str, np.ndarray],
               parameters: Dict[str, Any]) -> Future:
        if self._closed:
            raise RuntimeError("batcher is closed")
        item = _Pending(inputs, parameters)
        self._queue.put(item)
        return item.future

    def close(self) -> None:
        self._closed = True
        self._queue.put(None)  # wake the worker
        self._worker.join(timeout=5)
        # a submit() that passed the _closed check right before close() may
        # have enqueued behind the sentinel: fail it rather than strand it
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item.future.done():
                item.future.set_exception(RuntimeError("batcher closed"))

    # -- worker -------------------------------------------------------------
    def _collect(self) -> List[_Pending]:
        if self._carry is not None:
            first, self._carry = self._carry, None
        else:
            first = self._queue.get()
        if first is None:
            return []
        window = [first]
        rows = first.rows
        deadline = time.monotonic() + self._max_delay_s
        while rows < self._max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is None:
                self._queue.put(None)  # re-signal shutdown after this batch
                break
            if rows + nxt.rows > self._max_batch:
                # would overflow the model's declared cap: starts the next
                # window instead (declared max_batch_size is a contract)
                self._carry = nxt
                break
            window.append(nxt)
            rows += nxt.rows
        return window

    def _run(self) -> None:
        while True:
            window = self._collect()
            if not window:
                return
            # group by compatibility; each group executes once
            groups: Dict[Tuple, List[_Pending]] = {}
            for item in window:
                groups.setdefault(
                    _compat_key(item.inputs, item.parameters), []).append(item)
            for items in groups.values():
                self._run_group(items)

    def _run_group(self, items: List[_Pending]) -> None:
        t0 = time.perf_counter_ns()
        queue_ns = sum(t0 - it.enqueued_ns for it in items)
        try:
            if len(items) == 1:
                stacked = items[0].inputs
            else:
                stacked = {
                    name: np.concatenate([it.inputs[name] for it in items], axis=0)
                    for name in items[0].inputs
                }
            # safe: the group key pins identical parameters across items
            outputs = self._execute(stacked, items[0].parameters)
            exec_ns = time.perf_counter_ns() - t0
            batch_rows = sum(it.rows for it in items)
            if self._report is not None:
                self._report(batch_rows, exec_ns, queue_ns, len(items))
            offset = 0
            for it in items:
                sliced = {
                    name: np.asarray(arr)[offset:offset + it.rows]
                    for name, arr in outputs.items()
                }
                offset += it.rows
                it.future.set_result(sliced)
        except Exception as e:  # noqa: BLE001 — every caller must hear it
            for it in items:
                if not it.future.done():
                    it.future.set_exception(e)
