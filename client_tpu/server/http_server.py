"""HTTP/REST frontend for ServerCore: the KServe v2 protocol + extensions.

Implements the same route surface the reference client targets (SURVEY.md
§2.1 http_client rows): health, metadata, config, repository control, stats,
trace/log settings, shared-memory registration (system / cuda-format / tpu),
and two-part binary inference bodies with ``Inference-Header-Content-Length``.
"""

from __future__ import annotations

import gzip
import json
import re
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import unquote

import numpy as np

from ..utils import triton_to_np_dtype
from .core import InferError, ServerCore, _array_to_bytes, _bytes_to_array

_MODEL_RE = re.compile(r"^/v2/models/([^/]+)(?:/versions/([^/]+))?(?:/(.*))?$")
_SHM_RE = re.compile(
    r"^/v2/(systemsharedmemory|cudasharedmemory|tpusharedmemory)"
    r"(?:/region/([^/]+))?/(status|register|unregister)$"
)
_FAMILY = {
    "systemsharedmemory": "system",
    "cudasharedmemory": "cuda",
    "tpusharedmemory": "tpu",
}


def _generate_core_request(model, payload: Any) -> Dict[str, Any]:
    """Map a generate-extension JSON payload onto a core infer request.

    Reference protocol (tritonserver's HTTP generate extension,
    docs/protocol/extension_generate.md): 'id' and 'parameters' are
    reserved; every other key names an input tensor whose value is a JSON
    scalar or (nested) list. Shapes are conformed to the model's metadata
    by prepending singleton dims ([1,2,3] -> [1,3] for an INT32[1,-1]
    input), the KServe analog of the reference's flat-JSON mapping.

    Extension over the reference: an OBJECT value referencing a
    registered shared-memory region (``{"shared_memory_region": ...,
    "shared_memory_byte_size": ..., "shared_memory_offset": ...,
    "shape": [...]}``) resolves the tensor from that region exactly like
    the infer path's shm input parameters — the disaggregated
    prefill/decode client hands a multi-hundred-KiB KV cache to the
    decode stream this way instead of inflating it into JSON.
    Shared by the threaded and aio frontends.
    """
    if not isinstance(payload, dict):
        raise InferError("generate request must be a JSON object", 400)
    specs = {s.name: s for s in model.inputs()}
    params = payload.get("parameters", {})
    if not isinstance(params, dict):
        raise InferError("generate 'parameters' must be an object", 400)
    req: Dict[str, Any] = {"inputs": [], "parameters": dict(params)}
    if payload.get("id"):
        req["id"] = str(payload["id"])
    for key, value in payload.items():
        if key in ("id", "parameters"):
            continue
        spec = specs.get(key)
        if spec is None:
            raise InferError(
                f"unexpected generate input '{key}' for model "
                f"'{model.name}'", 400)
        if isinstance(value, dict):
            if "shared_memory_region" not in value:
                raise InferError(
                    f"generate input '{key}': object values must carry a "
                    "'shared_memory_region' reference", 400)
            shape = value.get("shape")
            if (not isinstance(shape, list) or not shape
                    or not all(isinstance(d, int) and not isinstance(d, bool)
                               and d >= 0 for d in shape)):
                raise InferError(
                    f"generate input '{key}': a shared-memory reference "
                    "needs an explicit 'shape' (list of non-negative "
                    "ints) — raw region bytes carry no shape", 400)
            req["inputs"].append({
                "name": key,
                "datatype": spec.datatype,
                "shape": list(shape),
                "shm": (
                    value["shared_memory_region"],
                    value.get("shared_memory_byte_size", 0),
                    value.get("shared_memory_offset", 0),
                ),
            })
            continue
        if spec.datatype == "BYTES":
            shaped = np.asarray(value, dtype=object)

            def as_bytes(v):
                if isinstance(v, str):
                    return v.encode("utf-8")
                if isinstance(v, (bytes, bytearray)):
                    return bytes(v)
                # JSON numbers/bools: their string form, NOT bytes(int)
                # (which would be that many NUL bytes)
                return str(v).encode("utf-8")

            arr = np.array(
                [as_bytes(v) for v in shaped.reshape(-1)],
                dtype=object).reshape(shaped.shape)
        else:
            try:
                arr = np.asarray(value, dtype=triton_to_np_dtype(spec.datatype))
            except (TypeError, ValueError) as e:
                raise InferError(
                    f"generate input '{key}' does not parse as "
                    f"{spec.datatype}: {e}", 400)
        while arr.ndim < len(spec.shape):
            arr = arr[np.newaxis, ...]
        req["inputs"].append({
            "name": key,
            "datatype": spec.datatype,
            "shape": list(arr.shape),
            "array": arr,
        })
    return req


def _generate_event(resp: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten one core response into the generate extension's JSON shape:
    metadata keys plus one flat key per output tensor (scalar when the
    tensor has a single element)."""
    out: Dict[str, Any] = {
        "model_name": resp["model_name"],
        "model_version": resp["model_version"],
    }
    if resp.get("id"):
        out["id"] = resp["id"]
    for entry in resp["outputs"]:
        arr = entry["array"]
        if entry["datatype"] == "BYTES":
            values = [
                v.decode("utf-8", "replace")
                if isinstance(v, (bytes, np.bytes_)) else str(v)
                for v in np.asarray(arr, dtype=object).reshape(-1)
            ]
        else:
            values = np.asarray(arr, dtype=np.float32).reshape(-1).tolist() \
                if entry["datatype"] == "BF16" \
                else np.asarray(arr).reshape(-1).tolist()
        out[entry["name"]] = values[0] if len(values) == 1 else values
    return out


def _sse_event(obj: Any) -> bytes:
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


def _generate_once(core, model_name: str, model_version: str,
                   core_req: Dict[str, Any]) -> Dict[str, Any]:
    """One-shot /generate semantics, shared by both frontends: pull at most
    TWO responses — a second yield already proves the generation belongs on
    /generate_stream, and closing there (rather than list()-ing a possibly
    minutes-long generation to throw it away) frees the model and the
    worker thread immediately."""
    import itertools

    gen = core.infer_stream(model_name, model_version, core_req)
    try:
        responses = list(itertools.islice(gen, 2))
    finally:
        gen.close()
    if len(responses) != 1:
        detail = ("no response" if not responses
                  else "more than one; use /generate_stream")
        raise InferError(
            f"generate expects exactly one response but model "
            f"'{model_name}' produced {detail}", 400)
    return _generate_event(responses[0])


def _decode_input(entry: Dict[str, Any], tail: memoryview, cursor: int) -> Tuple[Dict[str, Any], int]:
    """Convert one JSON input descriptor (+binary tail slice) to the core shape."""
    params = entry.get("parameters", {})
    out: Dict[str, Any] = {
        "name": entry["name"],
        "datatype": entry["datatype"],
        "shape": entry["shape"],
    }
    if "shared_memory_region" in params:
        out["shm"] = (
            params["shared_memory_region"],
            params.get("shared_memory_byte_size", 0),
            params.get("shared_memory_offset", 0),
        )
        return out, cursor
    size = params.get("binary_data_size")
    if size is not None:
        if isinstance(size, bool) or not isinstance(size, int) or size < 0:
            raise InferError(
                f"input '{entry['name']}': binary_data_size must be a "
                f"non-negative integer, got {size!r}", 400,
            )
        if cursor + size > len(tail):
            raise InferError(
                f"input '{entry['name']}': binary_data_size {size} overruns "
                f"the binary payload ({len(tail) - cursor} bytes remain)", 400,
            )
        raw = bytes(tail[cursor : cursor + size])
        out["array"] = _bytes_to_array(raw, entry["datatype"], entry["shape"])
        return out, cursor + size
    data = entry.get("data")
    if data is None:
        raise InferError(f"input '{entry['name']}' has no data", 400)
    if entry["datatype"] == "BYTES":
        arr = np.array(
            [d.encode("utf-8") if isinstance(d, str) else bytes(d) for d in _flatten(data)],
            dtype=np.object_,
        ).reshape(entry["shape"])
    else:
        arr = np.array(data, dtype=triton_to_np_dtype(entry["datatype"])).reshape(entry["shape"])
    out["array"] = arr
    return out, cursor


def _flatten(data):
    if isinstance(data, (list, tuple)):
        for item in data:
            yield from _flatten(item)
    else:
        yield data


def parse_infer_request(body: bytes, header_length: Optional[int]) -> Dict[str, Any]:
    """Parse a two-part infer body into the neutral core request dict."""
    if header_length is None:
        header = json.loads(body)
        tail = memoryview(b"")
    else:
        header = json.loads(body[:header_length])
        tail = memoryview(body)[header_length:]
    request: Dict[str, Any] = {
        "id": header.get("id", ""),
        "parameters": header.get("parameters", {}),
        "inputs": [],
    }
    cursor = 0
    for entry in header.get("inputs", []):
        decoded, cursor = _decode_input(entry, tail, cursor)
        request["inputs"].append(decoded)
    outputs = []
    binary_default = bool(request["parameters"].get("binary_data_output", False))
    for entry in header.get("outputs", []) or []:
        params = entry.get("parameters", {})
        spec: Dict[str, Any] = {
            "name": entry["name"],
            "binary": params.get("binary_data", binary_default),
            "classification": params.get("classification", 0),
        }
        if "shared_memory_region" in params:
            spec["shm"] = (
                params["shared_memory_region"],
                params.get("shared_memory_byte_size", 0),
                params.get("shared_memory_offset", 0),
            )
        outputs.append(spec)
    if outputs:
        request["outputs"] = outputs
    elif binary_default:
        request["outputs"] = None
        request["binary_default"] = True
    return request


def infer_request_encoding_prefs(request: Dict[str, Any]):
    """``(requested, binary_default)`` for ``encode_infer_response`` —
    shared by the HTTP frontend and the embedding API so identical request
    bytes always produce identically-encoded responses."""
    requested = request.get("outputs")
    binary_default = bool(
        request.get("binary_default")
        or request.get("parameters", {}).get("binary_data_output", False)
    )
    return requested, binary_default


def encode_infer_response(
    response: Dict[str, Any], requested: Optional[List[Dict[str, Any]]],
    binary_default: bool,
) -> Tuple[bytes, Optional[int]]:
    """Encode a core response dict into (body, json_header_length)."""
    req_by_name = {r["name"]: r for r in requested or []}
    header: Dict[str, Any] = {
        "model_name": response["model_name"],
        "model_version": response["model_version"],
    }
    if response.get("id"):
        header["id"] = response["id"]
    if response.get("parameters"):
        header["parameters"] = response["parameters"]
    out_entries = []
    tails: List[bytes] = []
    for out in response["outputs"]:
        entry: Dict[str, Any] = {
            "name": out["name"],
            "datatype": out["datatype"],
            "shape": out["shape"],
        }
        if "shm" in out:
            region, byte_size, offset = out["shm"]
            entry["parameters"] = {
                "shared_memory_region": region,
                "shared_memory_byte_size": byte_size,
            }
            if offset:
                entry["parameters"]["shared_memory_offset"] = offset
        else:
            spec = req_by_name.get(out["name"], {})
            binary = spec.get("binary", binary_default)
            arr = out["array"]
            if out["datatype"] in ("BF16",):
                binary = True  # no JSON representation
            if binary:
                payload = _array_to_bytes(arr, out["datatype"])
                tails.append(payload)
                entry["parameters"] = {"binary_data_size": len(payload)}
            else:
                if out["datatype"] == "BYTES":
                    entry["data"] = [
                        e.decode("utf-8", errors="replace") if isinstance(e, bytes) else str(e)
                        for e in arr.reshape(-1).tolist()
                    ]
                else:
                    entry["data"] = [v.item() for v in np.nditer(arr, order="C")]
        out_entries.append(entry)
    header["outputs"] = out_entries
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if not tails:
        return hj, None
    return hj + b"".join(tails), len(hj)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # TCP_NODELAY: without it, small control-message responses (the whole
    # point of the shm data plane) eat the 40ms Nagle+delayed-ACK stall
    # (reference sets it at http_client.cc PreRunProcessing)
    disable_nagle_algorithm = True
    core: ServerCore  # set by server factory

    def log_message(self, fmt, *args):  # quiet
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    # -- plumbing ----------------------------------------------------------
    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        encoding = self.headers.get("Content-Encoding")
        if encoding == "gzip":
            body = gzip.decompress(body)
        elif encoding == "deflate":
            body = zlib.decompress(body)
        return body

    def _send(self, status: int, body: bytes = b"", headers: Optional[Dict[str, str]] = None):
        # Honor Accept-Encoding (clients only send it when they asked for
        # response compression). Inference-Header-Content-Length refers to the
        # *uncompressed* body, matching the protocol.
        accept = self.headers.get("Accept-Encoding", "")
        headers = dict(headers or {})
        if body and "Content-Encoding" not in headers:
            if "gzip" in accept:
                body = gzip.compress(body)
                headers["Content-Encoding"] = "gzip"
            elif "deflate" in accept:
                body = zlib.compress(body)
                headers["Content-Encoding"] = "deflate"
        self.send_response(status)
        for k, v in headers.items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _send_json(self, obj: Any, status: int = 200):
        self._send(
            status,
            json.dumps(obj, separators=(",", ":")).encode("utf-8"),
            {"Content-Type": "application/json"},
        )

    def _send_error_json(self, e: Exception):
        status = e.status if isinstance(e, InferError) else 500
        self._send_json({"error": str(e)}, status)

    # -- GET ---------------------------------------------------------------
    def do_GET(self):
        self.server.request_began()
        try:
            self._route_get()
        finally:
            self.server.request_ended()

    def _route_get(self):
        core = self.core
        path = self.path.split("?", 1)[0]
        try:
            if path == "/v2" or path == "/v2/":
                return self._send_json(core.server_metadata())
            if path == "/metrics":
                # Prometheus scrape target; NOT gated on core.ready — a
                # scraper must see the drain (ready gauge -> 0), not errors
                return self._send(
                    200, core.metrics_registry().prometheus_text().encode(),
                    {"Content-Type":
                     "text/plain; version=0.0.4; charset=utf-8"})
            if path == "/v2/health/live":
                return self._send(200 if core.live else 503)
            if path == "/v2/health/ready":
                # ready is drainable: close()/drain() flips core.ready so
                # pool probes route away before the listener disappears
                return self._send(200 if (core.live and core.ready) else 503)
            if path == "/v2/models/stats":
                return self._send_json(core.statistics())
            if path == "/v2/trace/access":
                # traceparent-joined server spans (queue/compute ns +
                # wall_time_s): the doctor reads these to join its probe
                # trace and estimate client<->server clock skew
                return self._send_json(core.access_records())
            if path == "/v2/trace/setting":
                return self._send_json(core.trace_settings)
            if path == "/v2/logging":
                return self._send_json(core.log_settings)
            m = _SHM_RE.match(path)
            if m and m.group(3) == "status":
                return self._send_json(
                    core.region_status(_FAMILY[m.group(1)], unquote(m.group(2) or ""))
                )
            m = _MODEL_RE.match(path)
            if m:
                name, version, tail = unquote(m.group(1)), m.group(2) or "", m.group(3) or ""
                if tail == "ready":
                    return self._send(200 if core.model_ready(name, version) else 400)
                if tail == "config":
                    return self._send_json(core.model(name, version).config())
                if tail == "stats":
                    return self._send_json(core.statistics(name, version))
                if tail == "trace/setting":
                    return self._send_json(core.trace_settings)
                if tail == "":
                    return self._send_json(core.model(name, version).metadata())
            self._send_json({"error": f"unknown route {path}"}, 404)
        except Exception as e:
            self._send_error_json(e)

    # -- POST --------------------------------------------------------------
    def do_POST(self):
        self.server.request_began()
        try:
            self._route_post()
        finally:
            self.server.request_ended()

    def _route_post(self):
        core = self.core
        path = self.path.split("?", 1)[0]
        try:
            body = self._read_body()
            if path == "/v2/repository/index":
                return self._send_json(core.repository_index())
            m = re.match(r"^/v2/repository/models/([^/]+)/(load|unload)$", path)
            if m:
                if m.group(2) == "load":
                    payload = json.loads(body) if body else {}
                    if not isinstance(payload, dict):
                        raise InferError("load request body must be a JSON object", 400)
                    config = payload.get("parameters", {}).get("config")
                    core.load_model(unquote(m.group(1)), config=config)
                else:
                    core.unload_model(unquote(m.group(1)))
                return self._send_json({})
            if path == "/v2/trace/setting" or re.match(
                r"^/v2/models/[^/]+/trace/setting$", path
            ):
                settings = json.loads(body) if body else {}
                for k, v in settings.items():
                    core.trace_settings[k] = v
                return self._send_json(core.trace_settings)
            if path == "/v2/logging":
                settings = json.loads(body) if body else {}
                for k, v in settings.items():
                    core.log_settings[k] = v
                return self._send_json(core.log_settings)
            m = _SHM_RE.match(path)
            if m:
                family, action = _FAMILY[m.group(1)], m.group(3)
                region = unquote(m.group(2)) if m.group(2) else None
                payload = json.loads(body) if body else {}
                if action == "register":
                    if family == "system":
                        core.register_system_region(
                            region,
                            payload["key"],
                            payload.get("offset", 0),
                            payload["byte_size"],
                        )
                    else:
                        core.register_handle_region(
                            family,
                            region,
                            payload["raw_handle"]["b64"],
                            payload.get("device_id", 0),
                            payload["byte_size"],
                        )
                elif action == "unregister":
                    core.unregister_region(region or "", None if region else family)
                return self._send_json({})
            m = _MODEL_RE.match(path)
            if m and (m.group(3) or "") == "infer":
                return self._do_infer(unquote(m.group(1)), m.group(2) or "", body)
            if m and (m.group(3) or "") in ("generate", "generate_stream"):
                return self._do_generate(
                    unquote(m.group(1)), m.group(2) or "", body,
                    stream=m.group(3) == "generate_stream")
            self._send_json({"error": f"unknown route {path}"}, 404)
        except InferError as e:
            self._send_error_json(e)
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            self._send_json({"error": f"failed to parse request: {e}"}, 400)
        except Exception as e:
            self._send_json({"error": f"internal error: {e}"}, 500)

    def _do_infer(self, model_name: str, model_version: str, body: bytes):
        header_length = self.headers.get("Inference-Header-Content-Length")
        request = parse_infer_request(
            body, int(header_length) if header_length is not None else None
        )
        traceparent = self.headers.get("traceparent")
        if traceparent:
            # W3C trace context: the core attaches a server-side span
            # joined on this trace id (ServerCore.access_records)
            request["traceparent"] = traceparent
        requested, binary_default = infer_request_encoding_prefs(request)
        responses = self.core.infer(model_name, model_version, request)
        body_out, json_size = encode_infer_response(
            responses[0], requested, binary_default
        )
        headers = {"Content-Type": "application/json"}
        if json_size is not None:
            headers = {
                "Content-Type": "application/octet-stream",
                "Inference-Header-Content-Length": str(json_size),
            }
        # ORCA per-response load metrics (reference README.md:354-369): the
        # client opts in via the endpoint-load-metrics-format request header
        orca_format = self.headers.get("endpoint-load-metrics-format")
        if orca_format in ("json", "text"):
            headers["endpoint-load-metrics"] = self.core.orca_report(
                orca_format, model_name
            )
        self._send(200, body_out, headers)

    def _do_generate(
        self, model_name: str, model_version: str, body: bytes, stream: bool
    ):
        # generate extension (reference: tritonserver extension_generate);
        # the aio frontend serves the same routes — shared helpers above
        payload = json.loads(body) if body else {}
        core_req = _generate_core_request(
            self.core.model(model_name, model_version), payload)
        traceparent = self.headers.get("traceparent")
        if traceparent:
            # W3C trace context: the whole generation (streamed or not)
            # joins the client's stream span in ServerCore.access_records
            core_req["traceparent"] = traceparent
        if not stream:
            return self._send_json(
                _generate_once(self.core, model_name, model_version,
                               core_req))

        gen = self.core.infer_stream(model_name, model_version, core_req)

        # committed to a stream: chunked SSE, one event per response. The
        # 200 + event-stream headers go out BEFORE the first response is
        # computed, so header-timeout intermediaries see a live connection
        # through a slow first token; a pre-first-response failure becomes
        # an in-band error event. Once the headers are out NOTHING may
        # escape to do_POST's handler (its JSON error response would land
        # mid-chunked-body and corrupt the framing) — every failure below
        # is handled here.
        def chunk(data: bytes) -> None:
            self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))

        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self.wfile.flush()  # headers on the wire before next(gen) blocks
            item = None
            try:
                item = next(gen, None)
            except Exception as e:
                chunk(_sse_event({"error": str(e)}))
            while item is not None:
                chunk(_sse_event(_generate_event(item)))
                try:
                    item = next(gen, None)
                except Exception as e:
                    chunk(_sse_event({"error": str(e)}))
                    break
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            # client went away mid-stream (BrokenPipe/ConnectionReset/
            # Aborted/socket timeout): closing the generator below runs
            # the model's GeneratorExit path (cancel stats bucket)
            self.close_connection = True
        except Exception as e:
            # server-side failure after headers (e.g. event flattening):
            # best-effort in-band error, then drop the connection — the
            # chunked framing can no longer be trusted for keep-alive
            try:
                chunk(_sse_event({"error": str(e)}))
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                pass
            self.close_connection = True
        finally:
            gen.close()


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer + an in-flight request counter, so graceful
    drain can wait for outstanding requests instead of guessing.

    stdlib default listen backlog is 5; bursts of concurrent connections
    get RST'd without ``request_queue_size`` raised."""

    request_queue_size = 128

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()

    def request_began(self) -> None:
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()

    def request_ended(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._idle.set()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        return self._idle.wait(timeout)


class HttpInferenceServer:
    """An in-process threaded v2 HTTP server bound to localhost.

    Usage::

        server = HttpInferenceServer(ServerCore(default_model_zoo()))
        server.start()
        client = InferenceServerClient(server.url)
        ...
        server.stop()        # immediate
        # or: server.close() # graceful: drain ready, finish in-flight
    """

    def __init__(self, core: ServerCore, port: int = 0, verbose: bool = False):
        self.core = core
        handler = type("BoundHandler", (_Handler,), {"core": core})
        self._httpd = _TrackingHTTPServer(("127.0.0.1", port), handler)
        self._httpd.verbose = verbose
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "HttpInferenceServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="client_tpu_http_server", daemon=True
        )
        self._thread.start()
        return self

    def drain(self, grace_s: float = 0.0) -> None:
        """Flip ``v2/health/ready`` to 503 (``core.ready = False``) and wait
        ``grace_s`` so pool ready-probes route traffic away BEFORE the
        listener disappears. The server keeps answering everything else —
        including requests that race the probe window. Note: ``core`` may
        be shared by several frontends; draining one drains them all."""
        self.core.ready = False
        if grace_s > 0:
            time.sleep(grace_s)

    def stop(self) -> None:
        """Immediate shutdown (in-flight requests may be cut); the graceful
        path is :meth:`close`."""
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd.server_close()

    def close(self, grace_s: float = 0.5) -> None:
        """Graceful shutdown: drain (ready -> 503), wait ``grace_s`` for
        health pollers to route away, finish in-flight requests, then close
        the listener. SIGTERM handlers should call this, not ``stop``."""
        self.drain(grace_s)
        # finish in-flight requests BEFORE tearing the listener down: while
        # they drain, the server must keep answering /metrics and the
        # health routes (live=true, ready=false) — a scraper should see the
        # drain happen, not connection errors (shutdown() first would stop
        # accepting while slow in-flight requests were still finishing)
        self._httpd.wait_idle(timeout=10)
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd.server_close()

    def __enter__(self) -> "HttpInferenceServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
