"""In-process KServe v2 inference server with a JAX/XLA backend.

The reference is client-only and relies on an external ``tritonserver`` for
integration tests (SURVEY.md §4). This package makes the framework
self-contained: a protocol-complete v2 server whose model execution runs on
JAX (TPU when available), with the system/TPU shared-memory data planes.
Frontends: HTTP (``http_server``), GRPC (``grpc_server``).
"""

from .core import ServerCore
from .grpc_server import GrpcInferenceServer
from .http_server import HttpInferenceServer

__all__ = [
    "AioHttpInferenceServer",
    "GrpcInferenceServer",
    "HttpInferenceServer",
    "ServerCore",
]


def __getattr__(name):
    # lazy: the aio frontend needs aiohttp, which is an optional extra
    if name == "AioHttpInferenceServer":
        from .http_server_aio import AioHttpInferenceServer

        return AioHttpInferenceServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
