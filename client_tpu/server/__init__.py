"""In-process KServe v2 inference server with a JAX/XLA backend.

The reference is client-only and relies on an external ``tritonserver`` for
integration tests (SURVEY.md §4). This package makes the framework
self-contained: a protocol-complete v2 server whose model execution runs on
JAX (TPU when available), with the system/TPU shared-memory data planes.
Frontends: HTTP (``http_server``), GRPC (``grpc_server``).
"""

from .core import ServerCore
from .grpc_server import GrpcInferenceServer
from .http_server import HttpInferenceServer

__all__ = ["ServerCore", "GrpcInferenceServer", "HttpInferenceServer"]
