"""Standalone server CLI: serve the model zoo over HTTP + GRPC.

The framework's tritonserver stand-in for examples, the perf harness, and
development::

    python -m client_tpu.serve --http-port 8000 --grpc-port 8001 [--vision]

Ctrl-C stops it immediately; SIGTERM drains gracefully — ``v2/health/ready``
/ ``ServerReady`` flip to not-ready first (so multi-endpoint pools route
away), in-flight requests finish, then the listeners close.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="client_tpu.serve")
    parser.add_argument("--http-port", type=int, default=8000)
    parser.add_argument("--grpc-port", type=int, default=8001)
    parser.add_argument("--no-http", action="store_true")
    parser.add_argument("--no-grpc", action="store_true")
    parser.add_argument(
        "--vision", action="store_true",
        help="also serve the densenet_onnx vision model (first request compiles)",
    )
    parser.add_argument(
        "--tensor-parallel", type=int, default=1,
        help="shard vision-model weights over N devices (serving-side tp)",
    )
    parser.add_argument("--identity-fp32", action="store_true",
                        help="also serve a dynamic-shape FP32 identity model")
    parser.add_argument(
        "--long-context", action="store_true",
        help="also serve the ring/ulysses long_context_encoder (sp)",
    )
    parser.add_argument(
        "--attention", choices=("ring", "ulysses", "auto", "flash"),
        default="ring",
        help="sequence-parallel scheme for --long-context (flash = the "
        "single-device Pallas kernel)",
    )
    parser.add_argument(
        "--moe", action="store_true",
        help="also serve the expert-parallel moe_ffn model (ep)",
    )
    parser.add_argument(
        "--http-frontend", choices=("threaded", "aio"), default="threaded",
        help="threaded: best single-client latency; aio: higher sustained "
        "rate and tighter p99 at many concurrent connections",
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    from .models import default_model_zoo
    from .models.simple import IdentityModel
    from .server import (
        AioHttpInferenceServer,
        GrpcInferenceServer,
        HttpInferenceServer,
        ServerCore,
    )

    models = default_model_zoo()
    if args.identity_fp32:
        models.append(IdentityModel("identity_fp32", "FP32"))
    if args.vision:
        from .models.ensemble import build_image_ensemble

        models.extend(build_image_ensemble(tensor_parallel=args.tensor_parallel))
    if args.long_context:
        from .models.long_context import LongContextEncoderModel

        models.append(LongContextEncoderModel(attention=args.attention))
    if args.moe:
        from .models.moe import MoEFFNModel

        models.append(MoEFFNModel())
    core = ServerCore(models)

    servers = []
    if not args.no_http:
        if args.http_frontend == "aio":
            http = AioHttpInferenceServer(core, port=args.http_port)
        else:
            http = HttpInferenceServer(core, port=args.http_port, verbose=args.verbose)
        http.start()
        servers.append(http)
        print(f"HTTP  server ({args.http_frontend}) listening on {http.url}")
    if not args.no_grpc:
        grpc_srv = GrpcInferenceServer(core, port=args.grpc_port, verbose=args.verbose)
        grpc_srv.start()
        servers.append(grpc_srv)
        print(f"GRPC  server listening on {grpc_srv.url}")
    print(f"models: {', '.join(m.name for m in models)}")

    class _Drain(Exception):
        pass

    def on_sigterm(signum, frame):
        # disarm: systemd/k8s stop sequences often deliver repeat SIGTERMs;
        # a second one must not abort the graceful close already underway
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        raise _Drain()

    signal.signal(signal.SIGTERM, on_sigterm)
    draining = False
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    except _Drain:
        draining = True
    finally:
        # shutdown is underway: further signals must not abort it mid-stop
        # (the finally also guarantees every server stops on ANY exit path)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        if draining:
            # graceful: flip ready everywhere FIRST so pool probes route
            # away, then let each frontend finish in-flight work and close
            print("SIGTERM: draining (ready -> not-ready, finishing in-flight)")
            core.ready = False
            time.sleep(1.0)
        for s in servers:
            try:
                if draining:
                    s.close(grace_s=0.0)
                else:
                    s.stop()
            except Exception as e:
                print(f"error stopping {type(s).__name__}: {e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
