"""Sharded scatter-gather serving: one logical request across a replica mesh.

The pool (client_tpu.pool) treats replicas as interchangeable clones; this
module opens the scenario where they are NOT — a model (or batch) too big
for one worker, served by client-driven tensor/batch parallelism across
*processes*. A :class:`ShardLayout` is a ``PartitionSpec``-like declaration
mapping each input/output tensor axis to an ordered list of replica-pinned
endpoints; :class:`ShardedClient` / :class:`AioShardedClient` split one
logical ``infer()`` along those axes into per-shard KServe requests, fan
them out concurrently through the existing pool machinery (each shard
pinned to its endpoint via ``PoolClient.pinned_infer`` and staged zero-copy
through the shm arena's cached per-endpoint registrations), and gather the
shard responses into one logical result with exactness asserts::

    from client_tpu.pool import PoolClient
    from client_tpu.shard import ShardLayout, ShardedClient

    layout = ShardLayout(
        endpoints=["10.0.0.1:8000", "10.0.0.2:8000"],
        inputs={"TOKENS": 0},              # split rows across replicas
        outputs={"LOGITS": 0, "NEXT_TOKEN": 0},  # concat rows back
    )
    pool = PoolClient(layout.endpoints, protocol="http", shm_arena=True)
    client = ShardedClient(pool, layout)
    result = client.infer("decoder_lm_tp_prefill", inputs)
    result.as_numpy("LOGITS")              # lease-pinned zero-copy view

Semantics (docs/sharding.md has the full interaction matrix):

- **Failure is first-class and whole-request.** A lost/errored shard fails
  the LOGICAL request with a typed :class:`ShardFailed` naming the shard
  index and pinned endpoint — never a silent partial retry on another
  replica (the other replicas hold the *other* shards, not spares) and
  never a partial gather. In-endpoint resilience (the pool's
  ``endpoint_retry`` / breaker) still composes per shard, and every shard
  draws its timeout from ONE shared
  :class:`~client_tpu.resilience.AttemptBudget`.
- **Admission charges one token per logical request** (the pool's
  controller, when armed) — shards bypass the pool-level gate so a
  half-admitted scatter can never deadlock the controller against itself.
- **Hedging and coalescing are rejected, typed.** A hedged shard would
  race a replica that doesn't hold the shard's partition; a coalesced
  shard would stack rows across layouts. Both raise
  :class:`ShardConfigError` at construction.
- **Exactness asserts at gather.** Shard responses must agree on dtype and
  every non-sharded dimension; declared outputs must be present on every
  shard; replicated outputs must be bit-identical across shards (checked
  on read). Axis coverage is validated at scatter: explicit per-shard
  ranges must tile ``[0, L)`` with no gap and no overlap
  (:class:`ShardLayoutError`).
- **Observability**: the logical request is one span (frontend
  ``shard+<protocol>``) with ``shard_scatter`` / per-shard ``attempt`` /
  ``shard_gather`` phases — ``Telemetry.phase_breakdown()`` decomposes
  logical-request time into scatter, slowest-shard and gather legs — plus
  ``client_tpu_shard_*`` counters and the per-request shard-skew
  histogram.

This is Hermes-style pipelined inference for models that don't fit one
worker (arXiv:2409.04249) recast as a client-side protocol; the replay /
capacity methodology (arXiv:2210.04323) drives it via the ``sharded``
trace kind (client_tpu.trace) and ``perf.py --shard-layout``.
"""

from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import flight as _flight
from ._base import fold_infer_args
from .pool import _PoolClientBase, AioPoolClient, PoolClient
from .utils import InferenceServerException, triton_to_np_dtype

__all__ = [
    "AioShardedClient",
    "ShardAxis",
    "ShardConfigError",
    "ShardError",
    "ShardFailed",
    "ShardGatherError",
    "ShardLayout",
    "ShardLayoutError",
    "ShardedClient",
    "ShardedInferResult",
]

REPLICATED = None  # readable alias for "this tensor is not sharded"


class ShardError(InferenceServerException):
    """Base for every typed sharding error."""

    def __init__(self, msg: str, status: str = "SHARD"):
        super().__init__(msg, status=status)


class ShardLayoutError(ShardError):
    """The layout declaration (or the request's tensors against it) is
    invalid: unknown axis, uncovered axis span, overlapping ranges,
    endpoint/range count mismatch, undeclared tensor."""

    def __init__(self, msg: str):
        super().__init__(msg, status="SHARD_LAYOUT")


class ShardConfigError(ShardError):
    """Sharded serving was composed with something it rejects by design:
    hedging, the coalescing dispatcher, sequence requests, shm-bound
    caller tensors, or a non-pool substrate."""

    def __init__(self, msg: str):
        super().__init__(msg, status="SHARD_CONFIG")


class ShardGatherError(ShardError):
    """Shard responses disagree (dtype/shape/replicated-content mismatch,
    missing or undeclared outputs) — the gather refuses to fabricate a
    logical result from inconsistent pieces."""

    def __init__(self, msg: str):
        super().__init__(msg, status="SHARD_GATHER")


class ShardFailed(ShardError):
    """One shard's request failed, so the WHOLE logical request failed.

    ``shard`` is the shard index, ``url`` its pinned endpoint, ``cause``
    the underlying per-shard exception. The scatter-gather layer never
    retries a shard on a different replica (they hold different
    partitions) and never returns a partial gather."""

    def __init__(self, shard: int, url: str, cause: BaseException):
        super().__init__(
            f"shard {shard} (endpoint {url}) failed: "
            f"{type(cause).__name__}: {cause}",
            status="SHARD_FAILED")
        self.shard = shard
        self.url = url
        self.cause = cause


class ShardAxis:
    """One tensor's shard mapping: the axis to split, optionally with
    explicit per-shard ``ranges`` (``[(start, stop), ...]``, one per
    endpoint, in endpoint order). Without ranges the axis is split into
    contiguous near-equal blocks. Explicit ranges must tile the axis:
    start at 0, end at the axis length, and be contiguous — a gap is an
    uncovered-axis error, an overlap a double-covered one (both
    :class:`ShardLayoutError`, both checked per request against the real
    axis length)."""

    __slots__ = ("axis", "ranges")

    def __init__(self, axis: int,
                 ranges: Optional[Sequence[Tuple[int, int]]] = None):
        if not isinstance(axis, int) or axis < 0:
            raise ShardLayoutError(
                f"shard axis must be a non-negative int, got {axis!r}")
        self.axis = axis
        self.ranges = ([(int(a), int(b)) for a, b in ranges]
                       if ranges is not None else None)

    def __repr__(self) -> str:
        if self.ranges is None:
            return f"ShardAxis({self.axis})"
        return f"ShardAxis({self.axis}, ranges={self.ranges})"

    def resolve(self, name: str, length: int,
                n_shards: int) -> List[Tuple[int, int]]:
        """Per-shard ``(start, stop)`` blocks covering ``[0, length)``."""
        if self.ranges is not None:
            ranges = self.ranges
            if len(ranges) != n_shards:
                raise ShardLayoutError(
                    f"input {name!r}: {len(ranges)} explicit ranges for "
                    f"{n_shards} shard endpoints")
            cursor = 0
            for i, (start, stop) in enumerate(ranges):
                if stop <= start:
                    raise ShardLayoutError(
                        f"input {name!r} shard {i}: empty/negative range "
                        f"({start}, {stop})")
                if start < cursor:
                    raise ShardLayoutError(
                        f"input {name!r} shard {i}: range ({start}, {stop}) "
                        f"overlaps shard {i - 1} (covered through {cursor})")
                if start > cursor:
                    raise ShardLayoutError(
                        f"input {name!r} shard {i}: axis span "
                        f"[{cursor}, {start}) is uncovered")
                cursor = stop
            if cursor != length:
                raise ShardLayoutError(
                    f"input {name!r}: ranges cover [0, {cursor}) but the "
                    f"axis has length {length}")
            return list(ranges)
        if length < n_shards:
            raise ShardLayoutError(
                f"input {name!r}: axis {self.axis} has length {length} < "
                f"{n_shards} shards (every shard needs at least one slice)")
        base, extra = divmod(length, n_shards)
        ranges, cursor = [], 0
        for i in range(n_shards):
            size = base + (1 if i < extra else 0)
            ranges.append((cursor, cursor + size))
            cursor += size
        return ranges


AxisSpec = Union[int, None, ShardAxis]


def _as_axis(name: str, spec: AxisSpec) -> Optional[ShardAxis]:
    if spec is None:
        return None
    if isinstance(spec, ShardAxis):
        return spec
    if isinstance(spec, bool) or not isinstance(spec, int):
        raise ShardLayoutError(
            f"tensor {name!r}: axis must be an int, None (replicated) or "
            f"ShardAxis, got {spec!r}")
    return ShardAxis(spec)


class ShardLayout:
    """The PartitionSpec of a sharded deployment.

    ``endpoints``: ordered replica urls, one per shard (shard *i* is
    pinned to ``endpoints[i]`` forever — there is no failover target for
    a partition). ``inputs`` / ``outputs`` map tensor name -> axis
    (``int`` or :class:`ShardAxis`) or ``None`` for replicated tensors
    (inputs: same bytes to every shard; outputs: must come back
    bit-identical from every shard). ``check_replicated=False`` skips the
    replicated-output content comparison (metadata is still asserted)."""

    def __init__(self, endpoints: Sequence[str],
                 inputs: Dict[str, AxisSpec],
                 outputs: Dict[str, AxisSpec],
                 check_replicated: bool = True):
        self.endpoints = [str(u) for u in endpoints]
        if len(self.endpoints) < 1:
            raise ShardLayoutError("a shard layout needs >= 1 endpoint")
        if len(set(self.endpoints)) != len(self.endpoints):
            raise ShardLayoutError(
                "shard endpoints must be distinct: two shards pinned to "
                f"one replica is a partition error ({self.endpoints})")
        if not inputs:
            raise ShardLayoutError("a shard layout needs >= 1 input tensor")
        if not outputs:
            raise ShardLayoutError("a shard layout needs >= 1 output tensor")
        self.inputs: Dict[str, Optional[ShardAxis]] = {
            str(k): _as_axis(k, v) for k, v in inputs.items()}
        self.outputs: Dict[str, Optional[ShardAxis]] = {
            str(k): _as_axis(k, v) for k, v in outputs.items()}
        if all(v is None for v in self.inputs.values()):
            raise ShardLayoutError(
                "every input is replicated: nothing is sharded, use the "
                "pool directly")
        self.check_replicated = check_replicated

    @property
    def n_shards(self) -> int:
        return len(self.endpoints)

    # -- (de)serialization -------------------------------------------------
    @classmethod
    def parse(cls, spec: str, endpoints: Sequence[str],
              **kwargs) -> "ShardLayout":
        """Build a layout from a compact spec string (the CLI surface):
        ``"IN0=0,IN1=r->OUT0=0,OUT1=r"`` — tensor=axis pairs, ``r`` (or
        ``replicated``) for replicated tensors, inputs and outputs
        separated by ``->``."""
        ins, sep, outs = spec.partition("->")
        if not sep:
            raise ShardLayoutError(
                f"shard layout spec needs 'inputs->outputs', got {spec!r}")

        def side(text: str, label: str) -> Dict[str, AxisSpec]:
            mapping: Dict[str, AxisSpec] = {}
            for part in filter(None, (p.strip() for p in text.split(","))):
                name, eq, axis = part.partition("=")
                if not eq or not name.strip():
                    raise ShardLayoutError(
                        f"malformed {label} spec part {part!r} "
                        "(want NAME=axis or NAME=r)")
                axis = axis.strip().lower()
                if axis in ("r", "replicated", "none", "-"):
                    mapping[name.strip()] = None
                else:
                    try:
                        mapping[name.strip()] = int(axis)
                    except ValueError:
                        raise ShardLayoutError(
                            f"{label} {name.strip()!r}: axis {axis!r} is "
                            "not an int or 'r'") from None
            return mapping

        return cls(endpoints, side(ins, "input"), side(outs, "output"),
                   **kwargs)

    def describe(self) -> Dict[str, Any]:
        """JSON-ready topology (the doctor's ``shard`` section and the
        bench artifacts embed this)."""

        def one(spec: Optional[ShardAxis]) -> Any:
            if spec is None:
                return "replicated"
            if spec.ranges is None:
                return spec.axis
            return {"axis": spec.axis, "ranges": list(spec.ranges)}

        return {
            "shards": self.n_shards,
            "endpoints": list(self.endpoints),
            "inputs": {k: one(v) for k, v in self.inputs.items()},
            "outputs": {k: one(v) for k, v in self.outputs.items()},
        }


# -- gather-side logical result ----------------------------------------------
class ShardedInferResult:
    """One logical InferResult assembled from per-shard responses.

    ``as_numpy`` of a sharded output concatenates the shard views along
    the layout axis — into a fresh arena lease when the client has one,
    so repeated reads serve the SAME lease-pinned zero-copy view over the
    slab; replicated outputs return shard 0's (itself zero-copy when that
    response is arena/binary-backed) after a bit-equality check across
    shards. ``release()`` drops the gather leases and every shard
    result's arena leases."""

    def __init__(self, layout: ShardLayout, results: List[Any],
                 arena=None):
        self._layout = layout
        self._results = results
        self._arena = arena
        self._cache: Dict[str, np.ndarray] = {}
        self._gather_leases: List[Any] = []
        self._validate()

    # -- exactness asserts (metadata level, eager) -------------------------
    def _metas(self, name: str) -> List[Dict[str, Any]]:
        metas = []
        for i, res in enumerate(self._results):
            meta = res.get_output(name)
            if meta is None:
                raise ShardGatherError(
                    f"output {name!r} missing from shard {i} "
                    f"({self._layout.endpoints[i]})")
            metas.append(meta)
        return metas

    def _validate(self) -> None:
        declared = set(self._layout.outputs)
        returned = set()
        for res in self._results:  # EVERY shard: a lone misconfigured
            returned |= {o.get("name") for o in      # replica must not
                         res.get_response().get("outputs", [])}  # hide
        extra = returned - declared
        if extra:
            raise ShardGatherError(
                f"shard responses carry outputs the layout does not "
                f"declare: {sorted(extra)} (declare an axis or 'r' for "
                "each)")
        for name, spec in self._layout.outputs.items():
            metas = self._metas(name)
            dtypes = {m["datatype"] for m in metas}
            if len(dtypes) != 1:
                raise ShardGatherError(
                    f"output {name!r}: shards disagree on dtype "
                    f"({sorted(dtypes)})")
            shapes = [list(m["shape"]) for m in metas]
            ndims = {len(s) for s in shapes}
            if len(ndims) != 1:
                raise ShardGatherError(
                    f"output {name!r}: shards disagree on rank ({shapes})")
            ndim = ndims.pop()
            if spec is None:
                if any(s != shapes[0] for s in shapes):
                    raise ShardGatherError(
                        f"output {name!r} is replicated but shard shapes "
                        f"differ: {shapes}")
                continue
            if spec.axis >= ndim:
                raise ShardGatherError(
                    f"output {name!r}: gather axis {spec.axis} out of "
                    f"range for rank {ndim}")
            for i, s in enumerate(shapes):
                other = [d for j, d in enumerate(s) if j != spec.axis]
                ref = [d for j, d in enumerate(shapes[0])
                       if j != spec.axis]
                if other != ref:
                    raise ShardGatherError(
                        f"output {name!r}: shard {i} non-gather dims {s} "
                        f"disagree with shard 0 {shapes[0]}")

    # -- accessors ---------------------------------------------------------
    @property
    def shard_results(self) -> List[Any]:
        return list(self._results)

    def get_output(self, name: str) -> Optional[Dict[str, Any]]:
        spec = self._layout.outputs.get(name)
        if name not in self._layout.outputs:
            return None
        metas = self._metas(name)
        shape = list(metas[0]["shape"])
        if spec is not None:
            shape[spec.axis] = sum(m["shape"][spec.axis] for m in metas)
        return {"name": name, "datatype": metas[0]["datatype"],
                "shape": shape}

    def get_response(self) -> Dict[str, Any]:
        head = self._results[0].get_response()
        return {
            "model_name": head.get("model_name"),
            "model_version": head.get("model_version"),
            "shards": self._layout.n_shards,
            "outputs": [self.get_output(name)
                        for name in self._layout.outputs],
        }

    def _gather_dest(self, datatype: str, shape: List[int]):
        """A writable ndarray to concatenate into: a zero-copy view over a
        fresh arena lease when possible (pinned by the lease until
        :meth:`release`), else a plain allocation."""
        np_dtype = np.dtype(triton_to_np_dtype(datatype))
        if self._arena is None or np_dtype.itemsize == 0:
            return np.empty(shape, np_dtype)
        nbytes = max(1, int(np.prod(shape)) * np_dtype.itemsize)
        lease = self._arena.lease(nbytes)
        self._gather_leases.append(lease)
        return lease.as_numpy(np_dtype, shape)

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        if name in self._cache:
            return self._cache[name]
        spec = self._layout.outputs.get(name)
        if name not in self._layout.outputs:
            raise ShardGatherError(
                f"output {name!r} is not declared in the shard layout")
        arrays = [res.as_numpy(name) for res in self._results]
        if any(a is None for a in arrays):
            missing = [i for i, a in enumerate(arrays) if a is None]
            raise ShardGatherError(
                f"output {name!r}: shards {missing} returned no host "
                "data (non-arena shared-memory outputs cannot gather)")
        if spec is None:
            first = arrays[0]
            if self._layout.check_replicated:
                for i, arr in enumerate(arrays[1:], start=1):
                    if not np.array_equal(first, arr):
                        raise ShardGatherError(
                            f"replicated output {name!r}: shard {i} "
                            f"({self._layout.endpoints[i]}) disagrees "
                            "with shard 0 bit-for-bit")
            self._cache[name] = first
            return first
        shape = [int(d) for d in self.get_output(name)["shape"]]
        dtype = arrays[0].dtype
        if dtype == np.object_ or dtype.kind in ("S", "U"):
            out = np.concatenate(arrays, axis=spec.axis)
        else:
            datatype = self._metas(name)[0]["datatype"]
            if datatype == "BF16":
                out = np.concatenate(arrays, axis=spec.axis)
            else:
                dest = self._gather_dest(datatype, shape)
                np.concatenate(arrays, axis=spec.axis, out=dest)
                out = dest
        self._cache[name] = out
        return out

    def release(self) -> None:
        """Release the gather leases and every shard result's arena
        leases (views taken from :meth:`as_numpy` die with them)."""
        self._cache.clear()
        for lease in self._gather_leases:
            try:
                lease.release()
            except Exception:
                pass
        self._gather_leases = []
        for res in self._results:
            release = getattr(res, "release_arena", None)
            if release is not None:
                release()


# -- scatter-side helpers -----------------------------------------------------
def _input_array(inp) -> np.ndarray:
    """Recover the host array behind a staged InferInput (zero-copy for
    fixed-width dtypes: a frombuffer view over the already-serialized
    wire bytes)."""
    datatype = inp.datatype()
    if datatype == "BYTES":
        raise ShardConfigError(
            f"input {inp.name()!r}: BYTES tensors cannot be sharded "
            "(variable-width rows have no sliceable axis layout)")
    if inp._shared_memory_params() is not None:
        raise ShardConfigError(
            f"input {inp.name()!r} is bound to shared memory; the "
            "scatter layer owns staging — pass host-staged inputs "
            "(set_data_from_numpy)")
    raw = inp._get_binary_data()
    if raw is None:
        raise ShardConfigError(
            f"input {inp.name()!r} carries no binary payload; stage it "
            "with set_data_from_numpy(..., binary_data=True)")
    shape = list(inp.shape())
    if datatype == "BF16":
        from .utils import deserialize_bf16_tensor

        return deserialize_bf16_tensor(raw).reshape(shape)
    np_dtype = triton_to_np_dtype(datatype)
    return np.frombuffer(raw, dtype=np_dtype).reshape(shape)


def _release_quietly(lease) -> None:
    try:
        lease.release()
    except Exception:
        pass


class _ShardPlan:
    """One logical request's scatter: per-shard input lists plus the
    arena leases each shard must release once its wire request settled."""

    __slots__ = ("inputs", "leases")

    def __init__(self, n_shards: int):
        self.inputs: List[List[Any]] = [[] for _ in range(n_shards)]
        self.leases: List[List[Any]] = [[] for _ in range(n_shards)]


class _ShardedBase:
    """Scatter/gather logic shared by the sync and asyncio clients."""

    _AIO = False

    def __init__(self, client: _PoolClientBase, layout: ShardLayout):
        if not isinstance(client, _PoolClientBase):
            kind = type(client).__name__
            if "Batching" in kind:
                raise ShardConfigError(
                    "sharded requests cannot ride the coalescing "
                    "dispatcher: coalescing stacks rows across callers, "
                    "sharding partitions rows across replicas — wrap the "
                    "PoolClient itself")
            raise ShardConfigError(
                f"ShardedClient needs a PoolClient/AioPoolClient "
                f"substrate, got {kind}")
        if client._AIO != self._AIO:
            raise ShardConfigError(
                "sync ShardedClient needs a PoolClient and "
                "AioShardedClient an AioPoolClient (sync/aio mismatch)")
        if client._hedge is not None:
            raise ShardConfigError(
                "hedging is rejected for sharded serving: a hedge copy "
                "would race a replica that does not hold the shard's "
                "partition — build the pool without hedge=")
        pool_urls = {ep.url for ep in client.pool.endpoints}
        missing = [u for u in layout.endpoints if u not in pool_urls]
        if missing:
            raise ShardConfigError(
                f"shard layout pins endpoints the pool does not serve: "
                f"{missing}")
        self.inner = client
        self.layout = layout

    # -- composition rejections (typed) ------------------------------------
    def coalescing(self, **kwargs):
        raise ShardConfigError(
            "sharded requests cannot be coalesced: a batch window would "
            "stack rows across shard layouts")

    def generate_stream(self, *args, **kwargs):
        raise ShardConfigError(
            "generate_stream cannot be sharded: a decode stream's state "
            "lives on one replica (see ROADMAP item 4, disaggregated "
            "prefill/decode)")

    def start_stream(self, *args, **kwargs):
        raise ShardConfigError(
            "bidi streams cannot be sharded: stream state is "
            "replica-local")

    # -- delegation ---------------------------------------------------------
    @property
    def _FRONTEND(self) -> str:
        return "shard+" + self.inner._FRONTEND

    def telemetry(self):
        return self.inner.telemetry()

    def arena(self):
        return self.inner.arena()

    def admission(self):
        return self.inner.admission()

    def endpoint_stats(self):
        return self.inner.endpoint_stats()

    def describe(self) -> Dict[str, Any]:
        return self.layout.describe()

    def __getattr__(self, name: str):
        if name.startswith("_") or name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    # -- scatter ------------------------------------------------------------
    def _check_kwargs(self, kwargs) -> None:
        if kwargs.get("sequence_id"):
            raise ShardConfigError(
                "sequence requests cannot be sharded: sequence state is "
                "replica-local and a scatter would split it")
        for out in kwargs.get("outputs") or ():
            if out._shared_memory_params() is not None:
                raise ShardConfigError(
                    f"requested output {out.name()!r} is bound to shared "
                    "memory; sharded gathers own output placement")

    def _scatter(self, inputs) -> _ShardPlan:
        """Slice every input per the layout and stage each slice — through
        the arena fast path when the pool carries one (one host->slab copy
        per shard, registrations cached per (endpoint, region)), else as
        plain binary payloads."""
        layout = self.layout
        n = layout.n_shards
        arena = self.inner.arena()
        plan = _ShardPlan(n)
        try:
            self._scatter_into(plan, inputs, arena)
        except BaseException:
            for leases in plan.leases:
                for lease in leases:
                    _release_quietly(lease)
            raise
        return plan

    def _scatter_into(self, plan: _ShardPlan, inputs, arena) -> None:
        layout = self.layout
        n = layout.n_shards
        names = set()
        for inp in inputs:
            name = inp.name()
            names.add(name)
            if name not in layout.inputs:
                raise ShardLayoutError(
                    f"request input {name!r} is not declared in the "
                    "shard layout")
            spec = layout.inputs[name]
            arr = _input_array(inp)
            cls = type(inp)
            if spec is None:
                # replicated: stage ONCE, every shard rides the same slab
                lease = None
                if arena is not None:
                    lease = arena.lease(max(1, arr.nbytes))
                    try:
                        lease.write_numpy(arr)
                    except BaseException:
                        _release_quietly(lease)
                        raise
                try:
                    for i in range(n):
                        shard_inp = cls(name, list(arr.shape),
                                        inp.datatype())
                        if lease is not None:
                            # one extra ref per shard, released by that
                            # shard's settle (or the scatter cleanup)
                            plan.leases[i].append(lease.retain())
                            lease.bind_input(shard_inp)
                        else:
                            shard_inp.set_data_from_numpy(arr)
                        plan.inputs[i].append(shard_inp)
                finally:
                    if lease is not None:
                        # the staging ref is ALWAYS dropped here — on a
                        # mid-loop failure the shard refs are released by
                        # _scatter's cleanup, and this ref must not leak
                        # the slab forever
                        _release_quietly(lease)
                continue
            if spec.axis >= arr.ndim:
                raise ShardLayoutError(
                    f"input {name!r}: shard axis {spec.axis} out of range "
                    f"for shape {list(arr.shape)}")
            ranges = spec.resolve(name, arr.shape[spec.axis], n)
            index: List[Any] = [slice(None)] * arr.ndim
            for i, (start, stop) in enumerate(ranges):
                index[spec.axis] = slice(start, stop)
                piece = arr[tuple(index)]
                shard_inp = cls(name, list(piece.shape), inp.datatype())
                if arena is not None and piece.dtype.kind not in ("O",):
                    lease = arena.lease(max(1, piece.nbytes))
                    try:
                        lease.write_numpy(piece)
                    except BaseException:
                        _release_quietly(lease)
                        raise
                    plan.leases[i].append(lease)
                    lease.bind_input(shard_inp)
                else:
                    shard_inp.set_data_from_numpy(
                        np.ascontiguousarray(piece))
                plan.inputs[i].append(shard_inp)
        undeclared = set(layout.inputs) - names
        if undeclared:
            raise ShardLayoutError(
                f"layout inputs missing from the request: "
                f"{sorted(undeclared)}")

    def _shard_kwargs(self, kwargs, shard: int,
                      remaining: Optional[float]) -> Dict[str, Any]:
        kw = dict(kwargs)
        if remaining is not None:
            kw["client_timeout"] = remaining
        request_id = kw.get("request_id")
        if request_id:
            kw["request_id"] = f"{request_id}.s{shard}"
        return kw

    def _gather(self, results: List[Any]) -> ShardedInferResult:
        return ShardedInferResult(self.layout, results,
                                  arena=self.inner.arena())

    # -- observability -------------------------------------------------------
    def _span_begin(self, model_name: str):
        tel = self.inner.telemetry()
        if tel is None:
            return None, None
        return tel, tel.begin(self._FRONTEND, model_name, op="shard_infer")

    def _note_done(self, tel, span, marks: List[Tuple[int, int]],
                   error: Optional[BaseException]) -> None:
        if tel is None:
            return
        # the per-shard "attempt" sub-spans are appended HERE, on the
        # caller's thread, from the workers' completion marks: a straggler
        # shard settling after a fail-fast ShardFailed must never mutate a
        # span that finish() already queued for folding (its late mark is
        # simply dropped)
        marks = list(marks)
        if span is not None:
            for start_ns, end_ns in marks:
                span.phase("attempt", start_ns, end_ns)
        skew_s = None
        if error is None and marks:
            skew_s = (max(e for _, e in marks)
                      - min(e for _, e in marks)) * 1e-9
        tel.on_shard_result(self._FRONTEND, skew_s)
        if isinstance(error, ShardFailed):
            tel.on_shard_failed(error.url)
        tel.finish(span, error)


class ShardedClient(_ShardedBase):
    """Synchronous scatter-gather client over a :class:`PoolClient`.

    Shard fan-out runs on an internal thread pool (sized to the layout);
    the first shard failure cancels not-yet-started siblings and raises
    :class:`ShardFailed` immediately — in-flight siblings settle in the
    background and their staging leases release when they do."""

    _AIO = False

    def __init__(self, client: Union[PoolClient, Sequence[str]],
                 layout: ShardLayout, protocol: str = "http",
                 executor_workers: Optional[int] = None,
                 **pool_kwargs):
        """``executor_workers``: the shard fan-out thread pool size. Every
        logical request holds ``n_shards`` threads for its round trip, so
        a client shared by C concurrent callers needs at least
        ``C * n_shards`` workers or the callers queue behind each other
        (default: ``max(8, 4 * n_shards)`` — size it up for harnesses)."""
        owns = False
        if not hasattr(client, "infer"):
            urls = list(client)
            pool_kwargs.setdefault("shm_arena", True)
            client = PoolClient(urls or layout.endpoints,
                                protocol=protocol, **pool_kwargs)
            owns = True
        elif pool_kwargs:
            raise ShardConfigError(
                "pool kwargs are only accepted when ShardedClient builds "
                "the pool itself (pass urls, not a client)")
        try:
            super().__init__(client, layout)
        except BaseException:
            if owns:
                client.close()
            raise
        self._owns = owns
        self._executor_workers = (
            executor_workers if executor_workers
            else max(8, 4 * layout.n_shards))
        self._executor_lock = threading.Lock()
        self._executor: Optional[ThreadPoolExecutor] = None

    def _get_executor(self) -> ThreadPoolExecutor:
        with self._executor_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._executor_workers,
                    thread_name_prefix="client_tpu_shard")
            return self._executor

    def close(self) -> None:
        with self._executor_lock:
            if self._executor is not None:
                self._executor.shutdown(wait=False)
                self._executor = None
        self.inner.close()

    def __enter__(self) -> "ShardedClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- inference -----------------------------------------------------------
    def infer(self, model_name: str, inputs, *args,
              **kwargs) -> ShardedInferResult:
        kwargs = fold_infer_args(args, kwargs)
        self._check_kwargs(kwargs)
        scratch = _flight.layer_begin(
            self.inner.telemetry(), "shard", model_name)
        if scratch is None:
            return self._infer_admitted(model_name, inputs, kwargs)
        try:
            result = self._infer_admitted(model_name, inputs, kwargs)
        except BaseException as e:
            _flight.layer_commit(self.inner.telemetry(), scratch, error=e)
            raise
        _flight.layer_commit(self.inner.telemetry(), scratch)
        return result

    def _infer_admitted(self, model_name: str, inputs,
                        kwargs) -> ShardedInferResult:
        """The admission-gated engine behind :meth:`infer` (split out so
        the flight-recorder wrapper above owns one scratch per LOGICAL
        sharded request)."""
        inner = self.inner
        ctrl = inner.admission()
        if ctrl is None:
            return self._infer_sharded(model_name, inputs, kwargs)
        # ONE admission token covers the whole logical scatter-gather run
        # (shards bypass the pool gate via pinned_infer)
        deadline = inner._admission_deadline(kwargs.get("client_timeout"))
        t0_ns = time.perf_counter_ns()
        token = ctrl.acquire(kwargs.get("priority") or 0, deadline)
        admission_phase = ((t0_ns, time.perf_counter_ns())
                           if token.waited_s else None)
        t0 = time.monotonic()
        try:
            result = self._infer_sharded(model_name, inputs, kwargs,
                                         admission_phase)
        except BaseException as e:
            inner._admission_settle(
                token, t0, getattr(e, "cause", None) or e)
            raise
        inner._admission_settle(token, t0, None)
        return result

    def _infer_sharded(self, model_name, inputs, kwargs,
                       admission_phase=None) -> ShardedInferResult:
        from .resilience import AttemptBudget

        inner = self.inner
        layout = self.layout
        tel, span = self._span_begin(model_name)
        if span is not None and admission_phase is not None:
            span.phase("admission_queue", *admission_phase)
        budget = AttemptBudget(inner._budget_policy,
                               kwargs.get("client_timeout"))
        marks: List[Tuple[int, int]] = []
        error: Optional[BaseException] = None
        try:
            scatter_t0 = time.perf_counter_ns()
            plan = self._scatter(inputs)
            try:
                remaining = budget.attempt_timeout_s()  # raises once spent
            except BaseException:
                for leases in plan.leases:
                    for lease in leases:
                        _release_quietly(lease)
                raise

            def run_shard(i: int):
                url = layout.endpoints[i]
                if tel is not None:
                    tel.on_shard_subrequest(url)
                t_start = time.perf_counter_ns()
                try:
                    res = inner.pinned_infer(
                        url, model_name, plan.inputs[i],
                        **self._shard_kwargs(kwargs, i, remaining))
                finally:
                    for lease in plan.leases[i]:
                        _release_quietly(lease)
                # the shard sub-span is recorded as a completion mark; the
                # caller folds marks into "attempt" phases in _note_done
                marks.append((t_start, time.perf_counter_ns()))
                return res

            executor = self._get_executor()
            futures: List[Any] = []
            _flight.note("shard", "fanout", shards=layout.n_shards)
            try:
                for i in range(layout.n_shards):
                    _flight.note("shard", "dispatch", shard=i,
                                 url=layout.endpoints[i])
                    futures.append(executor.submit(run_shard, i))
            except BaseException:
                # a shard that never dispatched still owns staged leases
                for j in range(len(futures), layout.n_shards):
                    for lease in plan.leases[j]:
                        _release_quietly(lease)
                raise
            if span is not None:
                span.phase("shard_scatter", scatter_t0,
                           time.perf_counter_ns())
            pending = set(futures)
            failed: Optional[Tuple[int, BaseException]] = None
            while pending and failed is None:
                done, pending = wait(pending, return_when=FIRST_COMPLETED)
                for f in done:
                    exc = f.exception()
                    if exc is not None:
                        i = futures.index(f)
                        if failed is None or i < failed[0]:
                            failed = (i, exc)
            if failed is not None:
                # fail fast and WHOLE: cancel what never started (their
                # staging leases release here), let in-flight siblings
                # settle in the background — their results are dropped,
                # never partially gathered
                for f in pending:
                    if f.cancel():
                        i = futures.index(f)
                        for lease in plan.leases[i]:
                            _release_quietly(lease)
                shard_i, cause = failed
                raise ShardFailed(shard_i, layout.endpoints[shard_i],
                                  cause)
            gather_t0 = time.perf_counter_ns()
            _flight.note("shard", "gather", shards=layout.n_shards)
            result = self._gather([f.result() for f in futures])
            if span is not None:
                span.phase("shard_gather", gather_t0,
                           time.perf_counter_ns())
            return result
        except BaseException as e:
            error = e
            raise
        finally:
            self._note_done(tel, span, marks, error)


class AioShardedClient(_ShardedBase):
    """Asyncio twin of :class:`ShardedClient` over an
    :class:`~client_tpu.pool.AioPoolClient`: shard fan-out as tasks, so
    the first failure TRULY cancels the sibling shards mid-flight before
    raising :class:`ShardFailed`."""

    _AIO = True

    def __init__(self, client: Union[AioPoolClient, Sequence[str]],
                 layout: ShardLayout, protocol: str = "http",
                 **pool_kwargs):
        owns = False
        if not hasattr(client, "infer"):
            urls = list(client)
            pool_kwargs.setdefault("shm_arena", True)
            client = AioPoolClient(urls or layout.endpoints,
                                   protocol=protocol, **pool_kwargs)
            owns = True
        elif pool_kwargs:
            raise ShardConfigError(
                "pool kwargs are only accepted when AioShardedClient "
                "builds the pool itself (pass urls, not a client)")
        try:
            super().__init__(client, layout)
        except BaseException:
            if owns:
                # close() is a coroutine; schedule-or-drop is worse than
                # leaking here — abandon endpoints synchronously
                client._abandon(client.pool.endpoints)
            raise
        self._owns = owns

    async def close(self) -> None:
        await self.inner.close()

    async def __aenter__(self) -> "AioShardedClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- inference -----------------------------------------------------------
    async def infer(self, model_name: str, inputs, *args,
                    **kwargs) -> ShardedInferResult:
        kwargs = fold_infer_args(args, kwargs)
        self._check_kwargs(kwargs)
        scratch = _flight.layer_begin(
            self.inner.telemetry(), "shard", model_name)
        if scratch is None:
            return await self._infer_admitted(model_name, inputs, kwargs)
        try:
            result = await self._infer_admitted(model_name, inputs, kwargs)
        except BaseException as e:
            _flight.layer_commit(self.inner.telemetry(), scratch, error=e)
            raise
        _flight.layer_commit(self.inner.telemetry(), scratch)
        return result

    async def _infer_admitted(self, model_name: str, inputs,
                              kwargs) -> ShardedInferResult:
        """Async twin of the sync ``_infer_admitted`` split."""
        inner = self.inner
        ctrl = inner.admission()
        if ctrl is None:
            return await self._infer_sharded(model_name, inputs, kwargs)
        deadline = inner._admission_deadline(kwargs.get("client_timeout"))
        t0_ns = time.perf_counter_ns()
        token = await ctrl.acquire_async(
            kwargs.get("priority") or 0, deadline)
        admission_phase = ((t0_ns, time.perf_counter_ns())
                           if token.waited_s else None)
        t0 = time.monotonic()
        try:
            result = await self._infer_sharded(model_name, inputs, kwargs,
                                               admission_phase)
        except BaseException as e:
            inner._admission_settle(
                token, t0, getattr(e, "cause", None) or e)
            raise
        inner._admission_settle(token, t0, None)
        return result

    async def _infer_sharded(self, model_name, inputs, kwargs,
                             admission_phase=None) -> ShardedInferResult:
        from .resilience import AttemptBudget

        inner = self.inner
        layout = self.layout
        tel, span = self._span_begin(model_name)
        if span is not None and admission_phase is not None:
            span.phase("admission_queue", *admission_phase)
        budget = AttemptBudget(inner._budget_policy,
                               kwargs.get("client_timeout"))
        marks: List[Tuple[int, int]] = []
        error: Optional[BaseException] = None
        try:
            scatter_t0 = time.perf_counter_ns()
            plan = self._scatter(inputs)
            try:
                remaining = budget.attempt_timeout_s()
            except BaseException:
                for leases in plan.leases:
                    for lease in leases:
                        _release_quietly(lease)
                raise

            async def run_shard(i: int):
                url = layout.endpoints[i]
                if tel is not None:
                    tel.on_shard_subrequest(url)
                t_start = time.perf_counter_ns()
                try:
                    res = await inner.pinned_infer(
                        url, model_name, plan.inputs[i],
                        **self._shard_kwargs(kwargs, i, remaining))
                finally:
                    for lease in plan.leases[i]:
                        _release_quietly(lease)
                # completion mark only; _note_done folds these into
                # "attempt" phases on the caller's side (see sync twin)
                marks.append((t_start, time.perf_counter_ns()))
                return res

            _flight.note("shard", "fanout", shards=layout.n_shards)
            tasks = [asyncio.ensure_future(run_shard(i))
                     for i in range(layout.n_shards)]
            if span is not None:
                span.phase("shard_scatter", scatter_t0,
                           time.perf_counter_ns())
            try:
                await asyncio.wait(tasks,
                                   return_when=asyncio.FIRST_EXCEPTION)
                failed: Optional[Tuple[int, BaseException]] = None
                for i, t in enumerate(tasks):
                    if t.done() and not t.cancelled() \
                            and t.exception() is not None:
                        failed = (i, t.exception())
                        break
                if failed is not None:
                    # true cancellation: the sibling shards die mid-flight
                    for t in tasks:
                        t.cancel()
                    for t in tasks:
                        try:
                            await t
                        except BaseException:
                            pass
                    shard_i, cause = failed
                    raise ShardFailed(
                        shard_i, layout.endpoints[shard_i], cause)
            except asyncio.CancelledError:
                for t in tasks:
                    t.cancel()
                for t in tasks:
                    try:
                        await t
                    except BaseException:
                        pass
                raise
            gather_t0 = time.perf_counter_ns()
            _flight.note("shard", "gather", shards=layout.n_shards)
            result = self._gather([t.result() for t in tasks])
            if span is not None:
                span.phase("shard_gather", gather_t0,
                           time.perf_counter_ns())
            return result
        except BaseException as e:
            error = e
            raise
        finally:
            self._note_done(tel, span, marks, error)
